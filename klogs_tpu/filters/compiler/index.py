"""The shared multi-literal index: one sweep narrows every line to its
candidate pattern groups.

This is the host half of thousand-pattern mode ("Regular Expression
Indexing for Log Analysis", PAPERS.md): every guarded pattern
contributes an OR-set of mandatory literals (factors.guard_factors);
the index dedupes them across the set and sweeps a framed batch ONCE,
memmem-style but vectorized. A line is a candidate for group g iff
some member pattern's guard literal occurs inside it (or g is an
always-candidate group). False positives cost a redundant group scan;
false negatives are impossible — every guard is a NECESSARY condition,
so the downstream DFA/NFA engines see every line they could ever
match.

Sweep design. The hot loop must cost a FIXED small number of
vectorized passes over the payload, independent of K — everything
per-factor happens only at surviving positions, which a needle corpus
keeps rare. Three ideas carry that:

- **One rolling code array.** A single big-endian 4-byte code per
  payload position (built zero-copy from four ``frombuffer`` views —
  no per-position Python, ~2 passes of memory traffic). Wider probes
  derive from it instead of paying uint64 sweeps: a factor >= 8 bytes
  probes as a CONJUNCTION of two 4-byte half-window codes at distance
  4 — ``bloom_a[f[q]] & bloom_b[f[q+4]]`` reuses the same fold array
  at two offsets.
- **Rarity-anchored windows.** Each factor's probe window sits at its
  RAREST position (digit/punctuation-heavy, by a static log-text
  prior), not position 0 — ``latency=49`` probes on ``y=49``, not on
  the every-line prefix ``latenc…`` — so survivors track true
  occurrences, and minted rule families (``job-00001``, ``job-00002``,
  ...) spread across distinct codes instead of funneling through one
  shared-prefix bucket with a per-hit verify fan-out of hundreds.
- **Staged bloom gates + sorted-run extraction.** Stage 1 is ONE
  gather into a 64 KiB union bloom (all probe codes of every tier) +
  ONE nonzero — the only per-position work besides building the codes.
  Survivors (rare) re-probe per-tier blooms, pay a searchsorted into
  the exact code tables, and group by code via ONE argsort, sliced as
  runs — Python iteration touches only DISTINCT PRESENT codes, never
  rescans the hit array per code. The bloom index is the high uint16
  half of a Fibonacci-multiply fold, read as a zero-copy view of the
  product array.

Factors of 3 bytes (the minimum factors.MIN_FACTOR_LEN) have no 4-byte
window; they enter the short tier as all 256 one-byte extensions, so
the same code path covers them (the 4th byte is beyond the factor and
is verified as don't-care; it may even cross a line boundary — only
the factor's own bytes must sit inside the line). Padding the payload
with zeros similarly only ADDS candidate positions; every survivor is
verified exactly (full factor bytes + line bounds), so the sweep
over-approximates but never misses.

Cost shape: the sweep is O(payload) with small constants regardless of
K; group scans are O(candidate lines x candidate groups). On a
needle-finding corpus (the log-filter regime) almost every line has
zero candidate groups, so throughput approaches the sweep rate while
a scan-all-K configuration pays K/32 automata per line — the bench.py
K-axis (BENCH_K.json) quantifies exactly this gap.
"""

from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

import numpy as np

from klogs_tpu.filters.compiler.groups import GroupPlan, PatternInfo

if TYPE_CHECKING:
    from klogs_tpu.filters.compiler.dfa import DFATables

# Minimum factor width the sweep can probe: matches
# factors.MIN_FACTOR_LEN (every guard literal is at least 3 bytes).
GRAM = 3
# Probe window widths: the wide tier (two chained 4-byte codes) for
# factors that fit one, the narrow tier (one code) for the rest.
WIDE = 8
NARROW = 4
# Longest factor the sweep verifies — BY DEFINITION the factor
# extractor's truncation bound (over-long guards only exist because
# guard_factors returns un-truncated exact literals): such literals
# are cut to their rarest window of this width at index build — still
# a necessary condition, and it bounds the device verify at
# SWEEP_FACTOR_CAP/4 word compares.
from klogs_tpu.filters.compiler.factors import (  # noqa: E402
    MAX_FACTOR_LEN as SWEEP_FACTOR_CAP,
)
# Bloom fold width: 2^16 bytes = 64 KiB per table, cache-resident,
# ~1.5% load even at K=4096 (~one anchored code per factor) — and the
# fold is the HIGH uint16 half of a Fibonacci multiply, readable as a
# zero-copy strided view of the product array (no shift pass).
_BLOOM_BITS = 16
_FIB = 2654435761
_FIB32 = np.uint32(_FIB)

# Static rarity prior on log-like text for window anchoring, derived
# from the ONE scoring table (factors._byte_rarity: smaller = rarer).
# _anchor argmaxes a window sum, so negate — the argmax of -w is the
# window with the smallest (rarest) factors-score. One source of
# truth: a tweak to the factor prior re-anchors the sweep with it.
from klogs_tpu.filters.compiler.factors import _byte_rarity

_BYTE_RARITY = np.asarray([-_byte_rarity(b) for b in range(256)],
                          dtype=np.float64)


def _anchor(f: bytes, width: int) -> int:
    """Offset of the rarest ``width``-byte window of ``f`` (the probe
    code position — see module docstring)."""
    w = _BYTE_RARITY[np.frombuffer(f, dtype=np.uint8)]
    if len(f) <= width:
        return 0
    sums = np.convolve(w, np.ones(width), mode="valid")
    return int(np.argmax(sums))


def sweep_factor(f: bytes) -> bytes:
    """The exact bytes the sweep indexes for guard literal ``f``:
    over-long literals (past SWEEP_FACTOR_CAP) are cut to their rarest
    cap-width window — a substring of a mandatory literal is itself
    mandatory. Shared by the index build and the adaptive re-guard's
    ban test (the ban must name what the sweep actually probed)."""
    if len(f) > SWEEP_FACTOR_CAP:
        at = _anchor(f, SWEEP_FACTOR_CAP)
        return f[at:at + SWEEP_FACTOR_CAP]
    return f


def _fold1(code: int) -> int:
    """Bloom-table index of one 4-byte code (build-time scalar twin of
    the sweep's vectorized multiply-fold)."""
    return ((code * _FIB) & 0xFFFFFFFF) >> (32 - _BLOOM_BITS)


def _fold(codes: np.ndarray) -> np.ndarray:
    """Bloom index per code: high half of the wrapping Fibonacci
    product, read as a zero-copy strided view on little-endian hosts
    (no shift pass)."""
    prod = codes * _FIB32
    if _LITTLE:
        return prod.view(np.uint16)[1::2]
    return (prod >> np.uint32(16)).astype(np.uint16)


_LITTLE = np.little_endian


def _code_at(f: bytes, at: int) -> int:
    """The sweep's 4-byte code of factor ``f`` at offset ``at`` —
    NATIVE byte order, matching the zero-copy payload views."""
    return int.from_bytes(f[at:at + 4].ljust(4, b"\0"),
                          "little" if _LITTLE else "big")


# -- native SIMD sweep (klogs_tpu/native/_hostops.c) -------------------
#
# The native kernel consumes the SAME packed tables as the device sweep
# (SweepProgram), serialized into one content-defined blob, plus the
# Teddy stage-1 nibble masks. Exact verification makes all three
# implementations (numpy / native / device) produce identical masks.
_NATIVE_MAGIC = 0x4B535750
_NATIVE_VERSION = 2
_TEDDY_M = 4
# Fat-Teddy threshold: below this many factors the 8-bucket plane is
# not saturated and the thin kernel (one shuffle chain) wins; at or
# above it the blob packs a second bucket plane (16 buckets) and the
# kernel pays one extra shuffle chain for roughly half the stage-1
# survivors. KLOGS_SWEEP_BUCKETS=8|16 pins the mode for parity
# fuzzing and A/B benches.
_FAT_FACTOR_MIN = 64
_BUCKET_CHOICES = ("auto", "8", "16")
# KLOGS_NATIVE_SIMD: stage-1 implementation override. "auto" resolves
# to the best CPU level at call time; "off" forces the numpy sweep
# (the extension stays loaded for the other hot loops). "sse2" is
# accepted as an alias for the ssse3 tier (the kernel clamps to what
# the CPU really has, so it can only degrade — avx512 on a
# non-AVX-512 box runs avx2/ssse3/scalar, never faults).
_SIMD_CHOICES: "dict[str, int | None]" = {
    "auto": -1, "avx512": 3, "avx2": 2, "ssse3": 1, "sse2": 1,
    "scalar": 0, "off": None,
}
_warned_no_native = False


def native_sweep_buckets(n_factors: int) -> int:
    """Resolved stage-1 bucket count (8 or 16) for an index with
    ``n_factors`` factors: KLOGS_SWEEP_BUCKETS when pinned, else the
    _FAT_FACTOR_MIN threshold (strict dialect — a typo'd pin silently
    benching the wrong bucket mode would poison every A/B row)."""
    from klogs_tpu.utils.env import read

    raw = read("KLOGS_SWEEP_BUCKETS", "auto") or "auto"
    mode = raw.strip().lower()
    if mode not in _BUCKET_CHOICES:
        raise ValueError(
            f"KLOGS_SWEEP_BUCKETS={raw!r}: expected one of "
            f"{', '.join(_BUCKET_CHOICES)}")
    if mode == "auto":
        return 16 if n_factors >= _FAT_FACTOR_MIN else 8
    return int(mode)

# KLOGS_NATIVE_GROUPSCAN: the batched MultiDFA group-scan stage of the
# indexed engine (group_scan in _hostops.c). "auto" = native when the
# extension is loadable (quiet per-group Python loop otherwise, ONE
# loud notice per process), "native" = required (raise when
# unavailable — tests/benches that must time the kernel), "off" = the
# per-group dispatch loop, which is also the parity oracle.
_GROUPSCAN_CHOICES = ("auto", "native", "off")


def native_groupscan_mode() -> str:
    """Parsed KLOGS_NATIVE_GROUPSCAN (strict dialect: a typo'd knob
    silently timing the wrong confirm stage would poison every
    BENCH_K row)."""
    from klogs_tpu.utils.env import read

    raw = (read("KLOGS_NATIVE_GROUPSCAN", "auto") or "auto")
    mode = raw.strip().lower()
    if mode not in _GROUPSCAN_CHOICES:
        raise ValueError(
            f"KLOGS_NATIVE_GROUPSCAN={raw!r}: expected one of "
            f"{', '.join(_GROUPSCAN_CHOICES)}")
    return mode


# -- MultiDFA program blob (native batched group scan) -----------------
#
# The confirm-stage twin of native_sweep_blob(): every DFA-backed
# group's flat scan tables (DFATables from compiler/dfa.py), packed
# behind one validated header so group_scan in _hostops.c can walk the
# whole candidate matrix in ONE GIL-released call. Unlike the sweep
# blob the tables here can run to several MB, so the blob stays in
# NATIVE byte order and is strictly process-local (built and consumed
# in the same process, never persisted or sent anywhere) — no
# byte-swapping pass is ever paid. The build is content-defined
# (a pure function of the member tables); IndexedFilter caches it
# keyed by member-table identity and rebuilds only when a member's
# tables object changes (e.g. the DFA LRU refreshed it), reusing the
# bytes of unchanged members via ``chunks``.
_MDFA_MAGIC = 0x4B4D4446
_MDFA_VERSION = 1
_MDFA_HEADER_WORDS = 8
_MDFA_DESC_WORDS = 10


def multidfa_blob(tables: "list[DFATables]",
                  chunks: "dict[int, tuple[bytes, bytes, bytes]] | None"
                  = None) -> bytes:
    """Pack ``tables`` (one DFATables per program member, in candidate-
    matrix column order) into the MultiDFA program blob.

    Layout (i32 words, native order; mirrored by the MH_*/MD_* enums
    in _hostops.c): an 8-word header (magic, version, member count,
    total length, 4 reserved), then per member a 10-word descriptor
    (n_dfa, n_classes, start, end_class, wide, match_all, and 4-byte-
    aligned offsets of the row-major transition table, the accept
    flags, and the int32[256] byte->class map), then the concatenated
    arrays. ``chunks`` (keyed by ``id(table_set)``) caches each
    member's serialized arrays so an incremental rebuild re-serializes
    only refreshed members."""
    if not tables:
        raise ValueError("multidfa_blob needs at least one table set")
    M = len(tables)
    header = np.zeros(_MDFA_HEADER_WORDS + _MDFA_DESC_WORDS * M,
                      dtype=np.int32)
    parts: "list[bytes]" = []
    pos = header.nbytes

    def put(b: bytes) -> int:
        nonlocal pos
        at = pos
        parts.append(b)
        pos += len(b)
        pad = (-pos) % 4
        if pad:
            parts.append(bytes(pad))
            pos += pad
        return at

    for m, t in enumerate(tables):
        cached = chunks.get(id(t)) if chunks is not None else None
        if cached is None:
            cached = (np.ascontiguousarray(t.table).tobytes(),
                      np.ascontiguousarray(t.accept,
                                           dtype=np.uint8).tobytes(),
                      np.ascontiguousarray(t.byte_class,
                                           dtype=np.int32).tobytes())
            if chunks is not None:
                chunks[id(t)] = cached
        d = _MDFA_HEADER_WORDS + _MDFA_DESC_WORDS * m
        header[d + 0] = len(t.accept)
        header[d + 1] = t.n_classes
        header[d + 2] = t.start
        header[d + 3] = t.end_class
        header[d + 4] = 1 if t.table.dtype == np.uint32 else 0
        header[d + 5] = 1 if t.match_all else 0
        header[d + 6] = put(cached[0])
        header[d + 7] = put(cached[1])
        header[d + 8] = put(cached[2])
    header[0] = _MDFA_MAGIC
    header[1] = _MDFA_VERSION
    header[2] = M
    header[3] = pos
    blob = header.tobytes() + b"".join(parts)
    assert len(blob) == pos
    return blob


def native_simd_level() -> "int | None":
    """Parsed KLOGS_NATIVE_SIMD: -1 auto, 0/1/2/3 a pinned stage-1
    tier (scalar/ssse3/avx2/avx512),
    None = native sweep disabled. Malformed values raise naming the
    knob (strict dialect: a typo'd SIMD pin silently timing the wrong
    path would poison every benchmark row)."""
    from klogs_tpu.utils.env import read

    raw = read("KLOGS_NATIVE_SIMD", "auto") or "auto"
    try:
        return _SIMD_CHOICES[raw.strip().lower()]
    except KeyError:
        raise ValueError(
            f"KLOGS_NATIVE_SIMD={raw!r}: expected one of "
            f"{', '.join(sorted(_SIMD_CHOICES))}") from None


@dataclass
class SweepStats:
    """Narrowing outcome of one swept batch (observability)."""

    lines: int = 0
    groups: int = 0
    candidate_cells: int = 0  # candidate (line, group) scan units
    candidate_lines: int = 0  # lines with at least one candidate group
    # Per-group candidate counts of the batch ([G] int64, None when
    # not tallied): the engine's group-scan ordering reuses this
    # instead of re-reducing the multi-MB candidate matrix.
    col_cells: "np.ndarray | None" = None

    @property
    def narrowing_ratio(self) -> float:
        """Fraction of (line, group) scans the index could NOT rule
        out: 1.0 = no narrowing (scan everything), 0.0 = nothing to
        scan. Lower is better."""
        total = self.lines * self.groups
        return (self.candidate_cells / total) if total else 1.0


class _Tier:
    """Exact-code probe tables for one tier: entries are (code, fid,
    anchor) sorted by code, bucketed so entries sharing a code form
    one contiguous run."""

    def __init__(self, entries: "list[tuple[int, int, int]]") -> None:
        entries.sort()
        codes = np.asarray([e[0] for e in entries], dtype=np.uint64)
        self.fid = np.asarray([e[1] for e in entries], dtype=np.int64)
        self.anchor = np.asarray([e[2] for e in entries], dtype=np.int64)
        self.codes, starts = np.unique(codes, return_index=True)
        self.bucket_start = np.append(starts, len(entries))


class FactorIndex:
    """Compiled sweep tables for one analyzed, grouped pattern set.

    ``code_freq`` (optional, {native-endian 4-byte code: observed
    count}) feeds the adaptive RE-ANCHOR: probe windows are normally
    placed by the static log-text rarity prior, but the prior can
    misfire on a live corpus — a factor like ``errcode=00881`` anchored
    on its ``code`` window pays a bloom hit + hash probe at EVERY
    ``code=`` occurrence even though the full factor never verifies.
    When observed counts are supplied (the IndexedFilter measures them
    on the probation slab), each factor's window minimizes the
    OBSERVED density first and falls back to the static prior as the
    tie-break. Anchoring only moves the probe window WITHIN the
    factor, so necessity — and numpy/native/device mask parity, since
    all three consume tables built from the same anchors — is
    untouched."""

    def __init__(self, infos: "list[PatternInfo]", plan: GroupPlan,
                 code_freq: "dict[int, int] | None" = None) -> None:
        self._code_freq = code_freq or {}
        self.n_patterns = len(infos)
        self.n_groups = plan.n_groups
        # Always-candidate groups: the plan's (groups packed from
        # unguardable patterns) PLUS any group holding a pattern whose
        # info carries no guard — under an adaptive re-guard ban
        # (groups.reguard_infos) a member of a guarded-plan group can
        # lose its guard, and its whole group must then be a candidate
        # for every line or necessity breaks.
        always = set(int(g) for g in plan.always_groups)
        for info in infos:
            if info.guard is None:
                always.add(int(plan.group_of[info.index]))
        self.always_groups = np.asarray(sorted(always), dtype=np.int64)
        # Dedupe guard literals across the set; remember, per literal,
        # which patterns it guards (for the per-pattern matrix) and
        # which groups those patterns live in (for the group sweep).
        by_factor: "dict[bytes, list[int]]" = {}
        for info in infos:
            for f in info.guard or ():
                # Over-long factors (un-truncated exact literals) sweep
                # as their rarest SWEEP_FACTOR_CAP-byte window
                # (sweep_factor): a substring of a mandatory literal
                # is itself mandatory, so necessity is preserved, and
                # the cap bounds the verify word count on BOTH the
                # host and device paths (the two must verify identical
                # bytes for the device mask to equal the host mask bit
                # for bit).
                by_factor.setdefault(sweep_factor(f),
                                     []).append(info.index)
        self.factors: "list[bytes]" = sorted(by_factor)
        self.pattern_ids: "list[np.ndarray]" = [
            np.asarray(by_factor[f], dtype=np.int64) for f in self.factors]
        self.group_ids: "list[np.ndarray]" = [
            np.unique(plan.group_of[pids]).astype(np.int64)
            for pids in self.pattern_ids]
        self._factor_arrs = [
            np.frombuffer(f, dtype=np.uint8) for f in self.factors]
        # Guarded = appears in some factor's pattern set (every guard
        # member lists its patterns, so any guarded pattern is covered).
        # The complement drives always-candidate masks for BOTH the
        # plan-group sweep and any re-targeted device sweep program.
        self.guarded = np.zeros(self.n_patterns, dtype=bool)
        for pids in self.pattern_ids:
            self.guarded[pids] = True
        self._group_of = np.asarray(plan.group_of, dtype=np.int32)
        self._sweep_prog: "Optional[SweepProgram]" = None
        # Keyed by bucket count (8/16): fuzzing and A/B benches pin
        # KLOGS_SWEEP_BUCKETS between calls on one index, so each
        # resolved mode keeps its own immutable blob.
        self._native_blobs: "dict[int, bytes]" = {}
        # Which implementation produced the last group_candidates mask
        # ("native" or "numpy"; the device path reports itself).
        self.last_impl = "numpy"
        # Stage-1 survivor telemetry of the last NATIVE sweep
        # ({"survivors", "positions"}; None before the first one),
        # and the kernel-folded column reduction of the last native
        # group_candidates call ((colsums, candidate_lines); None
        # whenever the numpy oracle ran instead).
        self.last_sweep_stats: "Optional[dict[str, int]]" = None
        self._native_reduce: \
            "Optional[tuple[np.ndarray, int]]" = None

        # Stage-1 union bloom (one gather gates everything) + per-tier
        # discrimination blooms consulted only at surviving positions.
        self._bloom_u = np.zeros(1 << _BLOOM_BITS, dtype=np.uint8)
        self._bloom_a = np.zeros(1 << _BLOOM_BITS, dtype=np.uint8)
        self._bloom_b = np.zeros(1 << _BLOOM_BITS, dtype=np.uint8)
        self._bloom_n = np.zeros(1 << _BLOOM_BITS, dtype=np.uint8)
        # THE per-factor probe decision (tier + window anchor),
        # computed ONCE and consulted by every table builder — the
        # host tiers here, the native blob's teddy masks, and the
        # device SweepProgram — so no two implementations can ever
        # disagree on where a factor's window sits. A >= WIDE factor
        # normally probes the wide tier, but under observed densities
        # it DEMOTES to the narrow tier when its best 4-byte window is
        # rarer than its best 8-byte window HEAD ("ms code=418": every
        # 8B window starts in omnipresent template text, while the
        # narrow "=418" window is needle-rare). The verify is always
        # the full factor, so tier choice is purely a probe-cost
        # decision.
        self._probes: "list[tuple[str, int]]" = []
        for f in self.factors:
            if len(f) < NARROW:
                self._probes.append(("ext", 0))
            elif len(f) < WIDE:
                self._probes.append(
                    ("narrow", self._anchor_of(f, NARROW)))
            else:
                wat = self._anchor_of(f, WIDE)
                tier, at = "wide", wat
                if self._code_freq:
                    nat = self._anchor_of(f, NARROW)
                    if (self._code_freq.get(_code_at(f, nat), 0)
                            < self._code_freq.get(_code_at(f, wat), 0)):
                        tier, at = "narrow", nat
                self._probes.append((tier, at))
        wide_entries: "list[tuple[int, int, int]]" = []
        narrow_entries: "list[tuple[int, int, int]]" = []
        for fi, f in enumerate(self.factors):
            tier, at = self._probes[fi]
            if tier == "wide":
                hi, lo = _code_at(f, at), _code_at(f, at + 4)
                self._bloom_u[_fold1(hi)] = 1
                self._bloom_a[_fold1(hi)] = 1
                self._bloom_b[_fold1(lo)] = 1
                wide_entries.append(((hi << 32) | lo, fi, at))
            elif tier == "narrow":
                code = _code_at(f, at)
                self._bloom_u[_fold1(code)] = 1
                self._bloom_n[_fold1(code)] = 1
                narrow_entries.append((code, fi, at))
            else:
                # 3-byte factor: all 256 one-byte extensions (module
                # docstring) — the 4th byte is don't-care at verify.
                for ext in range(256):
                    code = _code_at(f + bytes([ext]), 0)
                    self._bloom_u[_fold1(code)] = 1
                    self._bloom_n[_fold1(code)] = 1
                    narrow_entries.append((code, fi, 0))
        self._wide = _Tier(wide_entries) if wide_entries else None
        self._narrow = _Tier(narrow_entries) if narrow_entries else None
        self.last_stats = SweepStats()

    @property
    def n_factors(self) -> int:
        return len(self.factors)

    def _anchor_of(self, f: bytes, width: int) -> int:
        """Probe-window offset for factor ``f``: observed corpus
        density first (class docstring), static rarity prior as the
        tie-break — or the prior alone when no observations exist.
        EVERY window consumer (tier build, teddy masks, device
        program) anchors through here so the implementations can
        never disagree on where a factor's window sits."""
        if not self._code_freq or len(f) <= width:
            return _anchor(f, width)
        w = _BYTE_RARITY[np.frombuffer(f, dtype=np.uint8)]
        sums = np.convolve(w, np.ones(width), mode="valid")
        best = 0
        best_key: "tuple[int, float] | None" = None
        for o in range(len(f) - width + 1):
            # Stage 1 (teddy + union bloom) gates on the window's
            # FIRST 4 bytes, so that code's observed count is the
            # survivor-cost driver for both tiers.
            key = (self._code_freq.get(_code_at(f, o), 0),
                   -float(sums[o]))
            if best_key is None or key < best_key:
                best_key, best = key, o
        return best

    # -- the sweep ----------------------------------------------------

    def _stage1(self, buf: bytes, n: int) -> np.ndarray:
        """Surviving positions of the stage-1 union-bloom gate, lane
        by lane: each byte offset k in 0..3 yields a zero-copy 4-byte
        view of the padded payload, whose fold gathers straight into
        the interleaved hit mask — the full per-position code array is
        never materialized (survivors recompute their exact codes from
        the raw bytes, O(survivors))."""
        g = np.empty(n, dtype=np.uint8)
        for k in range(4):
            lane = g[k::4]
            v = np.frombuffer(buf, dtype="<u4" if _LITTLE else ">u4",
                              offset=k, count=(len(buf) - k) // 4)
            lane[:] = self._bloom_u[_fold(v)[:len(lane)]]
        return np.nonzero(g)[0]

    @staticmethod
    def _codes_at(buf_arr: np.ndarray, s: np.ndarray) -> np.ndarray:
        """Native-endian 4-byte codes of positions ``s`` (vectorized
        over survivors; ``buf_arr`` is the padded payload bytes)."""
        b0 = buf_arr[s].astype(np.uint32)
        b1 = buf_arr[s + 1].astype(np.uint32)
        b2 = buf_arr[s + 2].astype(np.uint32)
        b3 = buf_arr[s + 3].astype(np.uint32)
        if _LITTLE:
            return (b0 | (b1 << np.uint32(8)) | (b2 << np.uint32(16))
                    | (b3 << np.uint32(24)))
        return ((b0 << np.uint32(24)) | (b1 << np.uint32(16))
                | (b2 << np.uint32(8)) | b3)

    def _hits(self, payload: bytes,
              offsets: np.ndarray) -> "list[tuple[int, np.ndarray]]":
        """(factor_id, line_ids) for every factor occurring inside a
        line of the framed batch. A fixed number of vectorized passes
        over the payload; Python iteration only over DISTINCT PRESENT
        codes (rare on a needle corpus) and their bucket factors."""
        arr = np.frombuffer(payload, dtype=np.uint8)
        n = len(arr)
        out: "list[tuple[int, np.ndarray]]" = []
        if n < GRAM or (self._wide is None and self._narrow is None):
            return out
        buf = bytes(payload) + bytes(8)  # payload may be a memoryview
        buf_arr = np.frombuffer(buf, dtype=np.uint8)
        # Stage 1: one union-bloom gather + one nonzero over the whole
        # payload; everything tier-specific runs on survivors only.
        s = self._stage1(buf, n)
        if not len(s):
            return out
        cs = self._codes_at(buf_arr, s)
        fs = _fold(cs)
        if self._wide is not None:
            wm = self._bloom_a[fs].astype(bool)
            ws = s[wm]
            if len(ws):
                lo = self._codes_at(buf_arr, ws + NARROW)
                bm = self._bloom_b[_fold(lo)].astype(bool)
                ws, lo = ws[bm], lo[bm]
                if len(ws):
                    exact = ((cs[wm][bm].astype(np.uint64)
                              << np.uint64(32)) | lo)
                    self._emit(self._wide, ws, exact, arr, n, offsets,
                               out)
        if self._narrow is not None:
            nm = self._bloom_n[fs].astype(bool)
            ns = s[nm]
            if len(ns):
                self._emit(self._narrow, ns, cs[nm].astype(np.uint64),
                           arr, n, offsets, out)
        return out

    def _emit(self, tier: _Tier, s: np.ndarray, exact: np.ndarray,
              arr: np.ndarray, n: int, offsets: np.ndarray,
              out: "list[tuple[int, np.ndarray]]") -> None:
        """Resolve bloom survivors ``s`` (exact codes ``exact``)
        against one tier's tables and append verified (fid, lines)."""
        slot = np.searchsorted(tier.codes, exact)
        slot_c = np.minimum(slot, len(tier.codes) - 1)
        ok = tier.codes[slot_c] == exact
        pos, kid = s[ok], slot_c[ok]
        if not len(pos):
            return
        # Sorted-run extraction: one stable argsort groups hit
        # positions by code; runs slice out per distinct code
        # (positions stay ascending within a run).
        order = np.argsort(kid, kind="stable")
        pos, kid = pos[order], kid[order]
        run_at = np.flatnonzero(np.diff(kid)) + 1
        bounds = np.concatenate(([0], run_at, [len(kid)]))
        for r in range(len(bounds) - 1):
            k = int(kid[bounds[r]])
            at = pos[bounds[r]:bounds[r + 1]]
            for bi in range(int(tier.bucket_start[k]),
                            int(tier.bucket_start[k + 1])):
                fi = int(tier.fid[bi])
                fa = self._factor_arrs[fi]
                L = len(fa)
                # Window position -> factor start; verify the FULL
                # factor bytes (window included: survivors may be
                # bloom false positives) and the line bounds.
                q = at - int(tier.anchor[bi])
                q = q[(q >= 0) & (q + L <= n)]
                if len(q):
                    body = arr[q[:, None] + np.arange(L)[None, :]]
                    q = q[(body == fa[None, :]).all(axis=1)]
                if not len(q):
                    continue
                line = np.searchsorted(offsets, q, side="right") - 1
                inside = (line >= 0) & (q + L <= offsets[line + 1])
                if inside.any():
                    out.append((fi, np.unique(line[inside])))

    def group_candidates(self, payload: bytes, offsets: np.ndarray,
                         impl: "str | None" = None) -> np.ndarray:
        """[B, G] bool: True where the line might match a pattern of
        group g (necessary condition). Always-candidate groups are True
        everywhere. Updates ``last_stats`` with the narrowing outcome.

        ``impl`` pins the sweep implementation: ``"native"`` (the SIMD
        kernel in the C extension — raises if unavailable), ``"numpy"``
        (the vectorized fallback, also the parity oracle), or None =
        auto: native when the extension is loadable and
        KLOGS_NATIVE_SIMD is not ``off``, else numpy with ONE loud
        notice per process. ``last_impl`` records what ran."""
        if impl not in (None, "native", "numpy"):
            raise ValueError(
                f"impl={impl!r}: expected native, numpy or None")
        B = len(offsets) - 1
        gm = None
        self._native_reduce = None
        if impl != "numpy":
            gm = self._native_candidates(payload, offsets,
                                         required=impl == "native")
        if gm is None:
            self.last_impl = "numpy"
            gm = np.zeros((B, self.n_groups), dtype=bool)
            if len(self.always_groups):
                gm[:, self.always_groups] = True
            for fi, lines in self._hits(payload, offsets):
                gm[np.ix_(lines, self.group_ids[fi])] = True
        else:
            self.last_impl = "native"
        # One column reduction serves the cell count, the engine's
        # scan ordering, AND — when some column is full, the common
        # case with an always-candidate group — the line count, which
        # would otherwise cost a second multi-MB reduction per batch.
        # The native kernel already folded it into the sweep (extended
        # stats buffer); only the numpy oracle pays the gm pass.
        if self._native_reduce is not None:
            colsums, cand_lines = self._native_reduce
        else:
            colsums = gm.sum(axis=0, dtype=np.int64)
            cand_lines = (B if B and len(colsums)
                          and int(colsums.max()) == B
                          else int(gm.any(axis=1).sum()) if B else 0)
        self.last_stats = SweepStats(
            lines=B, groups=self.n_groups,
            candidate_cells=int(colsums.sum()),
            candidate_lines=cand_lines,
            col_cells=colsums)
        return gm

    def native_ready(self) -> bool:
        """True when the native SIMD sweep will serve the next
        group_candidates call (cheap probe, no sweep) — callers size
        slabs by it (filters/indexed.py NATIVE_SLAB_LINES)."""
        from klogs_tpu.native import hostops

        return (native_simd_level() is not None and hostops is not None
                and hasattr(hostops, "sweep_candidates"))

    def group_candidates_packed(self, payload: bytes,
                                offsets: np.ndarray
                                ) -> "np.ndarray | None":
        """The sweep's RAW u32[B, ceil(G/32)] group bitset (bit g&31 of
        word g>>5 = group g candidacy, always-candidate bits pre-set),
        or None when the native kernel is unavailable — callers fall
        back to :meth:`group_candidates`. Same ``last_stats`` /
        ``last_impl`` bookkeeping as the bool form. The packed words
        feed the native group_scan's packed mode zero-copy, so the
        per-slab unpackbits (measured ~1 ms on a 64k-row slab at
        K=1024) disappears from the fast path entirely."""
        B = len(offsets) - 1
        self._native_reduce = None
        bits = self._native_packed(payload, offsets, required=False)
        if bits is None:
            return None
        self.last_impl = "native"
        colsums, cand_lines = self._native_reduce
        self.last_stats = SweepStats(
            lines=B, groups=self.n_groups,
            candidate_cells=int(colsums.sum()),
            candidate_lines=cand_lines,
            col_cells=colsums)
        return bits

    def _native_candidates(self, payload: bytes, offsets: np.ndarray,
                           required: bool = False) -> "np.ndarray | None":
        """One native-kernel sweep unpacked to [B, G] bool, or None
        when the fallback should run."""
        bits = self._native_packed(payload, offsets, required)
        if bits is None:
            return None
        # count= keeps the unpack a single contiguous [B, G] pass and
        # the bool view is free — no slice + astype copy per slab.
        gm = np.unpackbits(bits.view(np.uint8), axis=1,
                           bitorder="little", count=self.n_groups)
        return gm.view(bool)

    def sweep_packed_stateless(self, payload: bytes,
                               offsets: np.ndarray
                               ) -> "tuple | None":
        """One native-kernel sweep with NO shared-state side effects:
        returns (bits u32[B, W], colsums i64[G], cand_lines,
        survivors, positions), or None when the kernel is unavailable.

        This is the slab pipeline's prefetch stage
        (filters/indexed.py): a worker thread may run it on slab i+1
        while the main thread confirms slab i — the program blob is
        immutable bytes, the stats buffer is call-local, and the
        kernel releases the GIL for the whole scan, so the only
        ordering rule left is that the CALLER folds results into
        ``last_stats``/tallies in slab order (``adopt_sweep``)."""
        level = native_simd_level()
        from klogs_tpu.native import hostops

        if (level is None or hostops is None
                or not hasattr(hostops, "sweep_candidates")):
            return None
        off = np.ascontiguousarray(offsets, dtype=np.int32)
        B = len(off) - 1
        W = (self.n_groups + 31) // 32
        if B <= 0:
            return (np.zeros((0, W), dtype="<u4"),
                    np.zeros(self.n_groups, dtype=np.int64), 0, 0, 0)
        # Call-local stats buffer (the kernel may drop the GIL, so it
        # must never be shared across in-flight sweeps). Extended
        # layout u64[3 + 32*W]: [survivors, positions, candidate
        # lines, per-bit column sums] — the kernel folds the column
        # reduction into a ctz walk of the packed mask, replacing a
        # measured ~4-6 ms/slab strided numpy pass at K=1024.
        stats = np.zeros(3 + 32 * W, dtype=np.uint64)
        raw = hostops.sweep_candidates(
            self.native_sweep_blob(), payload, off, B, int(level),
            stats)
        return (np.frombuffer(raw, dtype="<u4").reshape(B, -1),
                stats[3:3 + self.n_groups].astype(np.int64),
                int(stats[2]), int(stats[0]), int(stats[1]))

    def adopt_sweep(self, res: tuple, B: int) -> np.ndarray:
        """Fold a ``sweep_packed_stateless`` result into the index's
        bookkeeping (``last_stats``/``last_impl``/``last_sweep_stats``)
        — called on the MAIN thread in slab order, so pipelined stats
        are byte-identical to the serial schedule's. Returns the packed
        bits."""
        bits, colsums, cand_lines, survivors, positions = res
        self.last_sweep_stats = {"survivors": survivors,
                                 "positions": positions}
        self._native_reduce = (colsums, cand_lines)
        self.last_impl = "native"
        self.last_stats = SweepStats(
            lines=B, groups=self.n_groups,
            candidate_cells=int(colsums.sum()),
            candidate_lines=cand_lines,
            col_cells=colsums)
        return bits

    def _native_packed(self, payload: bytes, offsets: np.ndarray,
                       required: bool = False) -> "np.ndarray | None":
        """One native-kernel sweep in the kernel's packed u32 form, or
        None when the fallback should run (sets ``_native_reduce`` as
        a side effect when it runs). The packed blob is built once per
        index and shared read-only across threads (the kernel releases
        the GIL for the whole scan)."""
        global _warned_no_native
        res = self.sweep_packed_stateless(payload, offsets)
        if res is None:
            if required:
                raise RuntimeError(
                    "native sweep unavailable (extension not loaded or "
                    "KLOGS_NATIVE_SIMD=off)")
            if native_simd_level() is not None and not _warned_no_native:
                # Loud, once: a fleet silently narrowing 5-10x slower
                # than provisioned is a capacity incident, not a detail.
                _warned_no_native = True
                from klogs_tpu.ui import term

                term.warning(
                    "native SIMD sweep unavailable (no C toolchain?); "
                    "narrowing on the numpy sweep for this process")
            return None
        bits, colsums, cand_lines, survivors, positions = res
        self.last_sweep_stats = {"survivors": survivors,
                                 "positions": positions}
        self._native_reduce = (colsums, cand_lines)
        return bits

    def native_sweep_blob(self) -> bytes:
        """The native kernel's table blob: the default SweepProgram's
        arrays serialized little-endian behind a fixed i32 header
        (offsets into the blob; layout mirrored by the enums at the
        top of the sweep section in _hostops.c), plus the Teddy
        stage-1 nibble masks — _TEDDY_M (4) window bytes x {low, high}
        nibble x 16 entries of bucket bitmasks per plane (128 bytes) —
        and the 64 KiB union bloom. Big indexes (see
        ``native_sweep_buckets``) pack a SECOND bucket plane: 16
        buckets split across two independent AND-chains, header words
        SH_BUCKETS/SH_TEDDY2_OFF, version 2. Cached per resolved
        bucket mode like ``_sweep_prog``; the blob is plain bytes, so
        it is immutable and thread-shareable by construction."""
        buckets = native_sweep_buckets(len(self.factors))
        cached = self._native_blobs.get(buckets)
        if cached is not None:
            return cached
        prog = self.sweep_program()
        # Stage-1 tables: 4-deep Teddy nibble masks over each factor's
        # anchored window (a 3-byte factor's 4th window byte is the
        # don't-care extension -> wildcard in position 3), plus the
        # union bloom (fold16 of every probe code of both tiers) the
        # confirm consults before any hash probe.
        #
        # Bucket assignment clusters factor families: factors are
        # ranked by their DISTINCT window bytes (sorted, so shared
        # guard-literal prefixes from groups.py land adjacent) and the
        # rank range is cut into equal bucket slices. Identical
        # windows always share a bucket, and unrelated families stop
        # diluting each other's nibble predicates — the confirm stage
        # verifies exactly, so assignment only moves the stage-1
        # false-positive rate, never the mask.
        teddy = np.zeros((2, _TEDDY_M, 2, 16), dtype=np.uint8)
        bloom = np.zeros(1 << _BLOOM_BITS, dtype=np.uint8)
        windows = [f[at:at + _TEDDY_M]
                   for (tier, at), f in zip(self._probes, self.factors)]
        rank = {w: i for i, w in enumerate(sorted(set(windows)))}
        n_win = max(1, len(rank))
        for fi, f in enumerate(self.factors):
            tier, at = self._probes[fi]
            w = windows[fi]
            plane, bit = divmod(rank[w] * buckets // n_win, 8)
            bucket = np.uint8(1 << bit)
            for j in range(_TEDDY_M):
                if j < len(w):
                    teddy[plane, j, 0, w[j] & 15] |= bucket
                    teddy[plane, j, 1, w[j] >> 4] |= bucket
                else:
                    teddy[plane, j, 0, :] |= bucket
                    teddy[plane, j, 1, :] |= bucket
            # Probe codes are the LITTLE-endian window codes of the
            # packed tiers (sweep_program's le_code), independent of
            # host byte order — same fold as the kernel's confirm.
            if tier != "ext":
                code = int.from_bytes(f[at:at + 4].ljust(4, b"\0"),
                                      "little")
                bloom[((code * _FIB) & 0xFFFFFFFF) >> 16] = 1
            else:
                for ext in range(256):
                    code = int.from_bytes(f + bytes([ext]), "little")
                    bloom[((code * _FIB) & 0xFFFFFFFF) >> 16] = 1

        header = np.zeros(34, dtype=np.int32)
        parts: "list[bytes]" = []
        pos = len(header.tobytes())

        def put(arr: np.ndarray, dt: str) -> int:
            nonlocal pos
            b = np.ascontiguousarray(arr, dtype=dt).tobytes()
            at = pos
            parts.append(b)
            pos += len(b)
            pad = (-pos) % 4
            if pad:
                parts.append(bytes(pad))
                pos += pad
            return at

        header[0] = _NATIVE_MAGIC
        header[1] = _NATIVE_VERSION
        header[2] = len(prog.fac_len)
        header[3] = prog.fac_words.shape[1]
        header[4] = len(prog.always_mask)
        header[5] = prog.n_groups
        header[6] = put(teddy[0].reshape(-1), "u1")
        header[7] = put(bloom, "u1")
        header[8] = put(prog.always_mask, "<u4")
        header[9] = put(prog.fac_len, "<i4")
        header[10] = put(prog.fac_words.reshape(-1), "<u4")
        header[11] = put(prog.fac_wmask.reshape(-1), "<u4")
        header[12] = put(prog.fac_groups.reshape(-1), "<u4")
        for base, tier in ((13, prog.narrow), (22, prog.wide)):
            header[base + 0] = len(tier.slot_key)
            header[base + 1] = len(tier.keys)
            header[base + 2] = len(tier.fid) if len(tier.keys) else 0
            header[base + 3] = tier.max_probe
            header[base + 4] = put(tier.slot_key, "<u4")
            header[base + 5] = put(tier.slot_eid, "<i4")
            header[base + 6] = put(tier.bucket_start, "<i4")
            header[base + 7] = put(tier.fid, "<i4")
            header[base + 8] = put(tier.anchor, "<i4")
        header[32] = buckets
        # The parser REQUIRES a zero second-plane offset in 8-bucket
        # mode (abi-conformance: no packed-but-unread words).
        header[33] = put(teddy[1].reshape(-1), "u1") if buckets == 16 else 0
        header[31] = pos
        blob = header.astype("<i4").tobytes() + b"".join(parts)
        assert len(blob) == pos
        self._native_blobs[buckets] = blob
        return blob

    def pattern_candidates(self, payload: bytes,
                           offsets: np.ndarray) -> np.ndarray:
        """[B, P] bool per-pattern candidate matrix (unguarded patterns
        all-True). The fine-grained form — tests assert its
        necessary-safety; the production scan path uses the coarser
        group matrix."""
        B = len(offsets) - 1
        pm = np.zeros((B, self.n_patterns), dtype=bool)
        pm[:, ~self.guarded] = True
        for fi, lines in self._hits(payload, offsets):
            pm[np.ix_(lines, self.pattern_ids[fi])] = True
        return pm

    # -- device sweep compilation ------------------------------------

    def sweep_program(self, group_of: "np.ndarray | None" = None,
                      n_groups: "int | None" = None) -> "SweepProgram":
        """Pack this index into the device-resident sweep tables
        (SweepProgram; consumed by klogs_tpu.ops.sweep).

        ``group_of`` retargets the factor -> group mapping: the default
        (None) packs against this index's OWN plan groups — the tier
        whose host twin is ``group_candidates`` and the parity oracle —
        while a caller fusing with the Pallas NFA kernel passes the
        grouped DeviceProgram's ``pattern_group`` map so the mask gates
        (tile, kernel-group) grid cells directly. Groups holding any
        UNGUARDED pattern land in ``always_mask`` (candidates for every
        line) under either mapping, so necessity is preserved exactly
        as on the host.

        Two probe tiers mirror the host sweep: factors >= WIDE key on
        the MIX of their two chained half-window codes (hi * FIB ^ lo —
        a 64-bit identity folded to one u32 key; collisions only deepen
        a bucket, the exact verify keeps the mask identical), shorter
        factors on their single narrow code. Without the wide mix,
        minted rule families sharing a rarest window funnel into one
        bucket and the device's STATIC probe loop pays the depth on
        every position (measured: max bucket 137 at K=1024 single-tier
        vs 2 two-tier). Factor bytes are packed as little-endian u32
        words + byte masks so the verify compares against the rolling
        code array itself — ceil(len/4) passes, not len.

        Codes are LITTLE-ENDIAN regardless of host byte order (the
        device builds its rolling codes from explicit byte shifts, so
        the layout must not depend on where the tables were packed).
        The default-map program is built once and cached."""
        default = group_of is None and n_groups is None
        if default and self._sweep_prog is not None:
            return self._sweep_prog
        gof = (self._group_of if group_of is None
               else np.asarray(group_of, dtype=np.int32))
        if len(gof) != self.n_patterns:
            raise ValueError(
                f"group_of maps {len(gof)} patterns, index has "
                f"{self.n_patterns}")
        G = int(n_groups) if n_groups is not None else (
            int(gof.max()) + 1 if len(gof) else 1)
        G = max(G, 1)
        GW = (G + 31) // 32
        always = np.zeros(GW, dtype=np.uint32)
        for p in np.nonzero(~self.guarded)[0]:
            g = int(gof[p])
            always[g // 32] |= np.uint32(1 << (g % 32))

        F = len(self.factors)
        kmax = max((len(f) for f in self.factors), default=1)
        n_words = (kmax + 3) // 4
        fac_len = np.zeros(max(F, 1), dtype=np.int32)
        fac_words = np.zeros((max(F, 1), n_words), dtype=np.uint32)
        fac_wmask = np.zeros((max(F, 1), n_words), dtype=np.uint32)
        fac_groups = np.zeros((max(F, 1), GW), dtype=np.uint32)
        # (key, fid, anchor) per tier.
        narrow: "list[tuple[int, int, int]]" = []
        wide: "list[tuple[int, int, int]]" = []

        def le_code(w: bytes) -> int:
            return int.from_bytes(w.ljust(4, b"\0"), "little")

        for fi, f in enumerate(self.factors):
            fac_len[fi] = len(f)
            for j in range(0, len(f), 4):
                w = f[j : j + 4]
                fac_words[fi, j // 4] = le_code(w)
                fac_wmask[fi, j // 4] = (1 << (8 * len(w))) - 1
            for g in np.unique(gof[self.pattern_ids[fi]]):
                fac_groups[fi, int(g) // 32] |= np.uint32(
                    1 << (int(g) % 32))
            tier, at = self._probes[fi]
            if tier == "wide":
                hi, lo = le_code(f[at : at + 4]), le_code(f[at + 4 : at + 8])
                wide.append((((hi * _FIB) & 0xFFFFFFFF) ^ lo, fi, at))
            elif tier == "narrow":
                narrow.append((le_code(f[at : at + 4]), fi, at))
            else:
                # 3-byte factor: all 256 one-byte extensions, anchor 0
                # (same don't-care-4th-byte rule as the host tiers; the
                # device pads each row with 4 zero columns, so the
                # extension byte exists even at the line's very end).
                for ext in range(256):
                    narrow.append((le_code(f + bytes([ext])), fi, 0))

        n_tier = pack_sweep_tier(narrow)
        w_tier = pack_sweep_tier(wide)
        # Per-tier verify bound: each tier's word loop only runs as
        # deep as its own longest member (demoted wide factors can
        # deepen the narrow tier; the max below tracks that).
        n_tier.n_words = max(
            (int(fac_len[fi]) + 3) // 4 for _, fi, _ in narrow) if narrow \
            else 0
        w_tier.n_words = max(
            (int(fac_len[fi]) + 3) // 4 for _, fi, _ in wide) if wide \
            else 0
        prog = SweepProgram(
            narrow=n_tier, wide=w_tier,
            fac_len=fac_len, fac_words=fac_words, fac_wmask=fac_wmask,
            fac_groups=fac_groups, always_mask=always, n_groups=G)
        if default:
            self._sweep_prog = prog
        return prog


def pack_sweep_tier(entries: "list[tuple[int, int, int]]",
                    hash_size: "int | None" = None) -> "SweepTier":
    """Pack one probe tier's (key, fid, anchor) entries: sorted unique
    keys with bucketed entry runs, plus the open-addressed hash table
    the device probes INSTEAD of a binary search (searchsorted lowers
    to log2 E dependent gather rounds; the hash probe is max_probe
    independent gathers into a cache/VMEM-resident table — measured
    ~8x cheaper on XLA CPU, same shape win on the TPU VPU).
    ``hash_size`` forces the table size (power of two) so mesh shards
    can be stacked shape-uniform; linear probing, keys unique."""
    entries = sorted(entries)
    keys_all = np.asarray([e[0] for e in entries], dtype=np.uint64)
    fid = np.asarray([e[1] for e in entries] or [0], dtype=np.int32)
    anchor = np.asarray([e[2] for e in entries] or [0], dtype=np.int32)
    keys, starts = np.unique(keys_all, return_index=True)
    bucket_start = np.append(starts, len(entries)).astype(np.int32)
    max_bucket = int(np.diff(bucket_start).max()) if len(keys) else 0
    H = hash_size if hash_size is not None else _sweep_hash_size(len(keys))
    if H & (H - 1) or H < len(keys):
        raise ValueError(f"hash_size {H} not a power of two >= {len(keys)}")
    bits = H.bit_length() - 1
    slot_key = np.zeros(H, dtype=np.uint32)
    slot_eid = np.full(H, -1, dtype=np.int32)
    max_probe = 0
    for eid, k in enumerate(keys):
        h = ((int(k) * _FIB) & 0xFFFFFFFF) >> (32 - bits)
        j = 0
        while slot_eid[(h + j) & (H - 1)] >= 0:
            j += 1
        slot_key[(h + j) & (H - 1)] = np.uint32(k)
        slot_eid[(h + j) & (H - 1)] = eid
        max_probe = max(max_probe, j + 1)
    return SweepTier(keys=keys.astype(np.uint32),
                     bucket_start=bucket_start, fid=fid, anchor=anchor,
                     slot_key=slot_key, slot_eid=slot_eid,
                     max_probe=max_probe, max_bucket=max_bucket)


def _sweep_hash_size(n_keys: int) -> int:
    """Power-of-two table ≥ 4x the key count (≤ 25% load keeps linear-
    probe clusters, and with them the device's unrolled probe depth,
    short)."""
    H = 16
    while H < 4 * n_keys:
        H *= 2
    return H


@dataclass
class SweepTier:
    """One probe tier of a SweepProgram: sorted unique probe keys with
    bucketed entry runs (factor id + window anchor per entry), the
    open-addressed hash table the device probes (slot_eid -1 = empty),
    and the static loop bounds — deepest probe cluster, deepest
    bucket, widest verify in u32 words."""

    keys: np.ndarray         # [E] u32, sorted unique
    bucket_start: np.ndarray  # [E+1] i32
    fid: np.ndarray          # [NE] i32 (min length 1)
    anchor: np.ndarray       # [NE] i32
    slot_key: np.ndarray     # [H] u32 (H a power of two)
    slot_eid: np.ndarray     # [H] i32, -1 = empty slot
    max_probe: int
    max_bucket: int
    n_words: int = 0


@dataclass
class SweepProgram:
    """Host-packed tables for the DEVICE literal sweep (compiled once
    per pattern set; klogs_tpu.ops.sweep turns them into device arrays
    and runs the jitted sweep).

    Layout: two SweepTiers — ``narrow`` keyed on the factor's rarest
    little-endian 4-byte window code (3-byte factors: all 256
    extensions), ``wide`` keyed on the Fibonacci mix of the two chained
    half-window codes of the rarest 8-byte window. ``fac_len`` /
    ``fac_words`` / ``fac_wmask`` carry the full factor bytes as padded
    u32 words + byte masks for the exact on-device verify, and
    ``fac_groups`` is each factor's target-group BITSET (32 groups per
    uint32 lane). ``always_mask`` holds groups owning unguarded
    patterns. No bloom ships to the device: the dense exact probe IS
    the gate there (ops/sweep.py module docstring)."""

    narrow: SweepTier
    wide: SweepTier
    fac_len: np.ndarray      # [F] i32 (min length 1)
    fac_words: np.ndarray    # [F, W] u32 LE factor words, zero-padded
    fac_wmask: np.ndarray    # [F, W] u32 byte masks (0 past the factor)
    fac_groups: np.ndarray   # [F, GW] u32 group bitsets
    always_mask: np.ndarray  # [GW] u32
    n_groups: int
