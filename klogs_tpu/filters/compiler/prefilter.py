"""Mandatory-pair extraction: the compile side of the two-phase filter.

A log filter selects RARE lines, so most of the NFA kernel's work is
spent proving non-matches. This module derives, per pattern, a CNF over
adjacent byte-pair containment — an AND of OR-clauses, each clause a set
of (S1, S2) byte-set pairs such that EVERY match of the pattern contains
two adjacent bytes x in S1, y in S2 for at least one pair of the clause.
A *necessary* condition only (the classic literal-prefilter idea,
rebuilt for byte-set regexes; no reference counterpart — the reference
streams unfiltered, /root/reference/cmd/root.go:359-374).

The runtime test compiles each clause into one LUT bit slot: the slot's
first/second LUTs are the UNION over the clause's pairs (a slot firing
on a cross-pair over-approximates the OR — still necessary-safe), and a
pattern's requirement is the AND of its clause slots. The device side is
a handful of 256-entry LUT lookups + bitwise ops per byte (VPU work,
~100x cheaper than the NFA matmuls); its verdict gates which batch tiles
the Pallas kernel actually scans (ops/pallas_nfa.py skip-tiles path).

Extraction is structural over the parser AST (CNF per node):

- Sym(bytes B): no clauses; begins/ends with a byte in B.
- Cat: clauses of all parts plus boundary singleton clauses (last-set of
  a definite part x first-set of the next definite part, with only
  empty-only nodes between).
- Alt: CNF of an alternation distributes: (A1&A2..)|(B1&B2..) becomes
  AND over all (Ai|Bj) — clause unions, capped for size.
- Star / optional: may match empty -> true (no clauses), breaks
  adjacency.
- Sentinels (^ $): match no byte; empty-only for factor purposes.

Pairs with huge byte-sets (e.g. involving `.`) are uselessly weak and
are pruned by a selectivity cap; clauses are ranked by a byte-rarity
prior so the retained ones discriminate on real log text.
"""

from dataclasses import dataclass

import numpy as np

from klogs_tpu.filters.compiler.parser import (
    Alt,
    Boundary,
    Cat,
    Epsilon,
    Star,
    Sym,
    parse,
)

# A pair side bigger than this matches too often to pay for its LUT bit.
MAX_SET_BYTES = 48
# LUT bitmask width: at most this many clause slots across the pattern
# set (W = ceil(slots/32) uint32 words per LUT entry).
MAX_PAIR_SLOTS = 512
# Keep at most this many (most selective) clauses per pattern.
MAX_CLAUSES_PER_PATTERN = 16
# Cap CNF size during Alt distribution.
MAX_CLAUSES_PER_NODE = 32
MAX_PAIRS_PER_CLAUSE = 8

Pair = tuple[frozenset, frozenset]
Clause = frozenset  # of Pair


def _byte_weight(b: int) -> float:
    """Rarity prior for ranking (smaller = rarer = more selective) on
    log-like text: punctuation/control rarest, then digits/uppercase,
    lowercase and space most common."""
    c = chr(b)
    if c.islower() or c == " ":
        return 4.0
    if c.isdigit() or c.isupper():
        return 2.0
    return 1.0


def _pair_weight(p: Pair) -> float:
    s1, s2 = p
    return (sum(_byte_weight(b) for b in s1) *
            sum(_byte_weight(b) for b in s2))


def _clause_weight(c: Clause) -> float:
    # OR of pairs: fires when any does — weakness adds up.
    return sum(_pair_weight(p) for p in c)


def _prune_clauses(clauses: set[Clause]) -> frozenset:
    """Drop clauses with oversized sets, cap counts."""
    ok = []
    for c in clauses:
        if len(c) > MAX_PAIRS_PER_CLAUSE:
            continue
        if any(len(a) > MAX_SET_BYTES or len(b) > MAX_SET_BYTES
               for a, b in c):
            continue
        ok.append(c)
    ok.sort(key=_clause_weight)
    return frozenset(ok[:MAX_CLAUSES_PER_NODE])


@dataclass(frozen=True)
class _Summary:
    """Per-node factor summary.

    kind: 'empty'   — matches ONLY the empty byte string (Epsilon,
                      sentinels): preserves adjacency, no first/last.
          'definite'— every match is a non-empty byte string whose first
                      byte is in `first` and last byte in `last`.
          'loose'   — may be empty / unknown shape: breaks adjacency.
    cnf: frozenset of clauses (each a frozenset of pairs); every matched
         string satisfies every clause.
    """

    kind: str
    first: frozenset = frozenset()
    last: frozenset = frozenset()
    cnf: frozenset = frozenset()


def _alt_cnf(cnfs: list[frozenset]) -> frozenset:
    """CNF of an alternation: fold pairwise distributions."""
    acc = cnfs[0]
    for nxt in cnfs[1:]:
        if not acc or not nxt:
            return frozenset()  # one side is 'true'
        out = {a | b for a in acc for b in nxt}
        acc = _prune_clauses(out)
    return acc


def _summarize(node: object) -> _Summary:
    if isinstance(node, (Epsilon, Boundary)):
        # \b/\B are zero-width: they preserve byte adjacency (a
        # mandatory pair across one remains mandatory) and add no
        # byte content of their own.
        return _Summary("empty")
    if isinstance(node, Sym):
        if node.sentinel is not None:
            return _Summary("empty")
        return _Summary("definite", first=node.bytes_, last=node.bytes_)
    if isinstance(node, Star):
        # Zero iterations possible: no mandatory content.
        return _Summary("loose")
    if isinstance(node, Alt):
        subs = [_summarize(p) for p in node.parts]
        cnf = _alt_cnf([s.cnf for s in subs])
        if all(s.kind == "definite" for s in subs):
            first = frozenset().union(*[s.first for s in subs])
            last = frozenset().union(*[s.last for s in subs])
            return _Summary("definite", first=first, last=last, cnf=cnf)
        if all(s.kind == "empty" for s in subs):
            return _Summary("empty", cnf=cnf)
        return _Summary("loose", cnf=cnf)
    if isinstance(node, Cat):
        subs = [_summarize(p) for p in node.parts]
        # Every part is traversed, so every part's clauses are mandatory.
        clauses: set[Clause] = set().union(*[s.cnf for s in subs]) if subs else set()
        # Boundary pairs: adjacent definite parts with only empty-only
        # parts between them become singleton clauses.
        pending_last: frozenset | None = None
        for s in subs:
            if s.kind == "definite":
                if pending_last is not None:
                    clauses.add(frozenset({(pending_last, s.first)}))
                pending_last = s.last
            elif s.kind == "loose":
                pending_last = None
            # 'empty': adjacency preserved, pending_last unchanged.
        firsts = next((s for s in subs if s.kind != "empty"), None)
        lasts = next((s for s in reversed(subs) if s.kind != "empty"), None)
        if firsts is None:  # all parts empty-only
            return _Summary("empty", cnf=_prune_clauses(clauses))
        kind = "definite" if (firsts.kind == "definite"
                              and lasts.kind == "definite") else "loose"
        return _Summary(
            kind,
            first=firsts.first if firsts.kind == "definite" else frozenset(),
            last=lasts.last if lasts.kind == "definite" else frozenset(),
            cnf=_prune_clauses(clauses),
        )
    raise TypeError(node)


def clauses_from_ast(node: object) -> "list[Clause]":
    """Mandatory pair-CNF of one PARSED pattern, most selective clause
    first — callers that already hold the AST (the regex index builds
    factors and clauses from one parse) skip the re-parse."""
    return sorted(_summarize(node).cnf, key=_clause_weight)


def mandatory_clauses(pattern: str, ignore_case: bool = False
                      ) -> list[Clause]:
    """Mandatory pair-CNF of one pattern, most selective clause first."""
    return clauses_from_ast(parse(pattern, ignore_case=ignore_case))


@dataclass
class PrefilterProgram:
    """Packed LUTs for the device candidate test.

    A line is a CANDIDATE for pattern p iff every clause slot k required
    by p fires: some adjacent (x, y) in the line has
    lut1[x,w] & lut2[y,w] bit set (slot k = word k//32, bit k%32).
    candidate(line) = OR_p AND_k. `usable` is False when some pattern
    yielded no clauses (its req mask would be all-zero =
    always-candidate, making the phase pointless)."""

    lut1: np.ndarray  # [256, W] uint32 — byte valid as a clause-pair first
    lut2: np.ndarray  # [256, W] uint32 — byte valid as a clause-pair second
    req: np.ndarray  # [P, W] uint32 — pattern p needs all these bits
    usable: bool
    # Clauses FOUND per pattern, before slot allocation (observability:
    # a zero here means the pattern truly has no mandatory pairs; a
    # nonzero count on an unusable program means the shared slot table
    # ran out — different user guidance).
    clause_counts: "list[int] | None" = None

    @property
    def n_words(self) -> int:
        return self.lut1.shape[1]


def compile_prefilter(patterns: list[str],
                      ignore_case: bool = False) -> PrefilterProgram:
    """Select up to MAX_PAIR_SLOTS clause slots across patterns and pack
    the LUTs.

    Slots are allocated GLOBALLY, not first-pattern-wins: clauses are
    deduplicated across the set and ranked by (best per-pattern rank,
    selectivity) — every pattern's rarest clause competes for a slot
    before ANY pattern's second-rarest. A pattern late in a large set
    whose best clause is shared (or rare) still gets req bits; under the
    old sequential scheme pattern #33+ of a diverse 512-clause set got
    nothing and silently disabled gating for everyone."""
    per_pattern = [mandatory_clauses(p, ignore_case) for p in patterns]
    # clause -> (best rank across patterns, weight): rank-0 clauses are
    # some pattern's most selective clause and allocate first.
    demand: dict[Clause, tuple[int, float]] = {}
    for clauses in per_pattern:
        for rank, clause in enumerate(clauses[:MAX_CLAUSES_PER_PATTERN]):
            key = (rank, _clause_weight(clause))
            prev = demand.get(clause)
            if prev is None or key < prev:
                demand[clause] = key
    order = sorted(demand, key=lambda c: demand[c])  # stable: dict order
    slot_of: dict[Clause, int] = {
        clause: i for i, clause in enumerate(order[:MAX_PAIR_SLOTS])}
    chosen: list[list[int]] = []
    usable = True
    for clauses in per_pattern:
        slots = [slot_of[c] for c in clauses
                 if c in slot_of][:MAX_CLAUSES_PER_PATTERN]
        if not slots:
            usable = False  # this pattern always passes -> no gating
        chosen.append(slots)
    W = max(1, -(-max(len(slot_of), 1) // 32))
    lut1 = np.zeros((256, W), dtype=np.uint32)
    lut2 = np.zeros((256, W), dtype=np.uint32)
    req = np.zeros((len(patterns), W), dtype=np.uint32)
    for clause, slot in slot_of.items():
        w, bit = slot // 32, np.uint32(1 << (slot % 32))
        for s1, s2 in clause:
            for b in s1:
                lut1[b, w] |= bit
            for b in s2:
                lut2[b, w] |= bit
    for i, slots in enumerate(chosen):
        for slot in slots:
            req[i, slot // 32] |= np.uint32(1 << (slot % 32))
    return PrefilterProgram(lut1=lut1, lut2=lut2, req=req, usable=usable,
                            clause_counts=[len(c) for c in per_pattern])


def candidate_matrix_host(pf: PrefilterProgram,
                          lines: list[bytes]) -> np.ndarray:
    """Reference (numpy, host) PER-PATTERN candidate matrix: [B, P]
    bool, True where the line satisfies pattern p's full clause
    requirement. The oracle for the device candidate matrix
    (ops.prefilter.candidate_matrix*) and the per-pattern narrowing
    primitive: column p False proves pattern p cannot match that line
    (necessary condition), so engines may skip it."""
    out = np.zeros((len(lines), pf.req.shape[0]), dtype=bool)
    for i, line in enumerate(lines):
        arr = np.frombuffer(line, dtype=np.uint8)
        if len(arr) < 2:
            present = np.zeros(pf.n_words, dtype=np.uint32)
        else:
            present = np.bitwise_or.reduce(
                pf.lut1[arr[:-1]] & pf.lut2[arr[1:]], axis=0)
        out[i] = ((present[None, :] & pf.req) == pf.req).all(axis=1)
    return out


def candidates_host(pf: PrefilterProgram, lines: list[bytes]) -> list[bool]:
    """Reference (numpy, host) any-pattern candidate test — the oracle
    for the device implementation and a quick selectivity probe."""
    return candidate_matrix_host(pf, lines).any(axis=1).tolist()
