"""--match pattern compiler: regex subset → Glushkov bit-parallel NFA
arrays for the JAX/Pallas batch engine (SURVEY.md §2 'Pattern
compiler' / §7 step 5)."""

from klogs_tpu.filters.compiler.glushkov import (
    NFAProgram,
    compile_patterns,
    reference_match,
)
from klogs_tpu.filters.compiler.parser import RegexSyntaxError, parse

__all__ = [
    "NFAProgram",
    "RegexSyntaxError",
    "compile_patterns",
    "parse",
    "reference_match",
]
