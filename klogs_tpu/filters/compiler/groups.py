"""Pattern grouping for thousand-pattern mode.

One automaton over K patterns stops scaling long before K reaches
production alerting-set sizes: subset construction over a union NFA
blows up combinatorially (the 32-pattern north-star set already
determinizes to ~8.5k states), and the grouped TPU kernel's MXU cost
grows with total positions. The fix — per "Regular Expression Indexing
for Log Analysis" (PAPERS.md) and Hyperscan's bucketed literal engines
— is to partition the set into bounded GROUPS, compile one table per
group, and let the factor index (index.py) narrow each line to its
candidate groups so engines scan a handful of groups, not K patterns.

Grouping heuristics (plan_groups):

- **Factor overlap:** guarded patterns are ordered by their primary
  guard literal, so patterns sharing factors land in the same group —
  one present factor lights up one group, not a smear across many.
  Shared byte structure also keeps the per-group byte classifier (and
  hence DFA alphabet) small: byte-classifier compatibility falls out
  of literal adjacency.
- **Bounded compile:** groups cap both member count and total Glushkov
  positions, so per-group subset construction stays small and
  rebuildable; a group that still overflows its DFA state budget
  degrades to a combined-`re` scan of just that group (engine side).
- **Segregated residuals:** patterns with no guard (nullable shapes,
  case-folded literals) make their whole group an always-candidate —
  grouping them together confines the damage instead of poisoning
  groups of well-guarded patterns. Patterns outside the compiler's
  RE2 subset group separately again (their group can never compile a
  DFA and goes straight to `re`).
"""

from dataclasses import dataclass, field

import numpy as np

from klogs_tpu.filters.compiler.factors import factors_from_ast, guard_factors
from klogs_tpu.filters.compiler.parser import RegexSyntaxError, parse
from klogs_tpu.filters.compiler.prefilter import clauses_from_ast

# Group budgets: member cap matches the north-star set size (a group is
# "one yesterday's-whole-pattern-set worth" of work); the position cap
# keeps per-group subset construction comfortably inside the DFA state
# budget for log-like patterns.
MAX_GROUP_PATTERNS = 32
MAX_GROUP_POSITIONS = 384


@dataclass(frozen=True)
class PatternInfo:
    """Per-pattern index analysis (one parse feeds everything).

    guard: OR-set of literals — every match contains at least one — or
           None when the pattern cannot be guarded (always-candidate).
    positions: Glushkov position count, None when the pattern is
           outside the compiler subset (no DFA/TPU table possible).
    factors / clauses: extraction counts for observability.
    """

    index: int
    pattern: str
    guard: "tuple[bytes, ...] | None"
    positions: "int | None"
    factors: int
    clauses: int


def analyze(patterns: "list[str]", ignore_case: bool = False,
            banned: "object | None" = None) -> "list[PatternInfo]":
    """Parse each pattern once; extract guard factors, pair-CNF clause
    count, and automaton size. Patterns the compiler cannot parse get
    (guard=None, positions=None) and ride the `re` fallback path.
    ``banned`` (a ``bytes -> bool`` predicate) vetoes guard literals —
    see factors.guard_factors; necessity holds under any ban."""
    from klogs_tpu.filters.compiler.glushkov import compile_patterns

    out: "list[PatternInfo]" = []
    for i, pat in enumerate(patterns):
        try:
            ast = parse(pat, ignore_case=ignore_case)
        except (RegexSyntaxError, ValueError):
            out.append(PatternInfo(i, pat, None, None, 0, 0))
            continue
        guard = guard_factors(ast, banned)
        n_factors = len(factors_from_ast(ast))
        n_clauses = len(clauses_from_ast(ast))
        try:
            positions: "int | None" = compile_patterns(
                [pat], ignore_case=ignore_case).n_states
        except (RegexSyntaxError, ValueError):
            positions = None
        out.append(PatternInfo(
            i, pat, tuple(guard) if guard is not None else None,
            positions, n_factors, n_clauses))
    return out


def reguard_infos(infos: "list[PatternInfo]", ignore_case: bool = False,
                  banned: "object | None" = None) -> "list[PatternInfo]":
    """Re-run ONLY guard extraction over already-analyzed patterns
    (the IndexedFilter's adaptive re-guard): positions / factor /
    clause counts are invariant under a ban, so the expensive
    per-pattern automaton sizing from ``analyze`` is reused and the
    rebuild costs one parse per pattern. The group plan stays valid —
    it partitions pattern INDICES — but a pattern whose guard vanishes
    under the ban must make its group always-candidate; FactorIndex
    derives that from the infos themselves."""
    out: "list[PatternInfo]" = []
    for info in infos:
        if info.guard is None and info.positions is None:
            out.append(info)  # unparseable: nothing to re-extract
            continue
        try:
            ast = parse(info.pattern, ignore_case=ignore_case)
        except (RegexSyntaxError, ValueError):
            out.append(info)
            continue
        guard = guard_factors(ast, banned)
        out.append(PatternInfo(
            info.index, info.pattern,
            tuple(guard) if guard is not None else None,
            info.positions, info.factors, info.clauses))
    return out


@dataclass
class GroupPlan:
    """Partition of the pattern set into compile groups.

    groups: pattern indices per group (original order within a group).
    group_of: [P] int32, pattern index -> group id.
    always_groups: group ids holding at least one unguarded pattern —
        the index must treat these as candidates for every line.
    """

    groups: "list[list[int]]" = field(default_factory=list)
    group_of: np.ndarray = field(
        default_factory=lambda: np.zeros(0, dtype=np.int32))
    always_groups: "tuple[int, ...]" = ()

    @property
    def n_groups(self) -> int:
        return len(self.groups)


def plan_groups(infos: "list[PatternInfo]",
                max_group_patterns: int = MAX_GROUP_PATTERNS,
                max_group_positions: int = MAX_GROUP_POSITIONS
                ) -> GroupPlan:
    """Partition analyzed patterns into bounded, factor-clustered
    groups (see module docstring for the heuristics)."""
    guarded = [i for i in infos if i.guard is not None
               and i.positions is not None]
    bare = [i for i in infos if i.guard is None and i.positions is not None]
    alien = [i for i in infos if i.positions is None]
    # Factor-overlap clustering: contiguous packing over the
    # primary-guard sort order, NOT first-fit across the whole set —
    # adjacency in the sort IS the overlap signal.
    guarded.sort(key=lambda i: (i.guard[0], i.index))

    groups: "list[list[int]]" = []

    def pack(bucket: "list[PatternInfo]") -> None:
        cur: "list[int]" = []
        load = 0
        for info in bucket:
            pos = info.positions or 1
            if cur and (len(cur) >= max_group_patterns
                        or load + pos > max_group_positions):
                groups.append(cur)
                cur, load = [], 0
            cur.append(info.index)
            load += pos
        if cur:
            groups.append(cur)

    pack(guarded)
    n_guarded_groups = len(groups)
    pack(bare)  # parseable but unguardable: always-candidate groups
    pack(alien)  # outside the compiler subset: always-candidate + `re`

    group_of = np.zeros(len(infos), dtype=np.int32)
    for g, members in enumerate(groups):
        for p in members:
            group_of[p] = g
    always = tuple(range(n_guarded_groups, len(groups)))
    return GroupPlan(groups=groups, group_of=group_of,
                     always_groups=always)
