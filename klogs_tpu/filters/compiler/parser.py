"""Regex parser for the --match pattern compiler.

Parses the RE2-style subset (no backreferences, no lookaround) into a
small AST over *byte sets* and *sentinel symbols*. Anchors are not
assertions here: ``^`` and ``$`` parse to ordinary symbols matching
virtual BEGIN/END sentinels that the engine feeds around each line, so
Glushkov construction needs no special cases and patterns like ``a^b``
(never matches) or ``^a*$`` fall out correct by construction. The one
place symbol semantics would diverge from re's idempotent assertions —
an anchor directly (or across nullable-only content) after another
anchor, e.g. ``^^``, ``$$``, ``$^``, ``^a?^`` — is rejected at compile
time (glushkov), keeping the contract that every accepted pattern
behaves exactly like re.

Supported syntax: literals, ``.``, escapes (\\d \\D \\w \\W \\s \\S
\\t \\n \\r \\f \\v \\0 \\xHH and escaped punctuation), word-boundary
assertions ``\\b`` / ``\\B`` (compiled to static edge constraints in
glushkov.py — no runtime cost), character classes ``[...]`` with
ranges and negation (``[\\b]`` is backspace, as in re), grouping
``(...)`` / ``(?:...)`` / ``(?P<name>...)`` (captures are irrelevant
to boolean matching; duplicate names reject as in re), comments
``(?#...)``, scoped flag groups over ``i`` (ignore-case)
and ``s`` (DOTALL) — ``(?i:...)``, ``(?-i:...)``, ``(?s:...)``,
``(?i-s:...)`` etc. — alternation ``|``, quantifiers ``* + ? {m} {m,}
{m,n}`` (lazy variants accepted — laziness is irrelevant for boolean
matching), anchors ``^ $`` plus ``\\A`` / ``\\Z`` (≡ ^/$ in the
single-line bytes domain), and whole-pattern ``(?i)`` / ``(?s)`` /
``(?si)`` prefixes.

The reference has no counterpart (filtering is new per the north star);
the CPU baseline is Python ``re`` (≙ Go ``regexp`` in klogs' world,
/root/reference/cmd/root.go:366 being the unfiltered write).
"""

from dataclasses import dataclass


class RegexSyntaxError(ValueError):
    pass


# Sentinel symbol kinds (distinct from any byte value).
BEGIN = "BEGIN"
END = "END"


@dataclass(frozen=True)
class Sym:
    """Leaf: matches one input symbol — either any byte in ``bytes_``
    (a frozenset of ints) or the BEGIN/END sentinel."""

    bytes_: frozenset = frozenset()
    sentinel: str | None = None


@dataclass(frozen=True)
class Epsilon:
    pass


@dataclass(frozen=True)
class Boundary:
    """Zero-width word-boundary assertion: ``\\b`` (negate=False)
    requires the adjacent symbols to differ in word-category,
    ``\\B`` (negate=True) requires them to agree. BEGIN/END sentinels
    count as non-word, exactly like re's edge-of-string rule."""

    negate: bool = False


@dataclass(frozen=True)
class Cat:
    parts: tuple


@dataclass(frozen=True)
class Alt:
    parts: tuple


@dataclass(frozen=True)
class Star:
    inner: object


def _is_bare_assertion(node: object) -> bool:
    """A bare anchor or \\b/\\B — re's 'nothing to repeat' targets;
    a group containing one ((?:\\b)?) is legal and wrapped in _atom."""
    return isinstance(node, Boundary) or (
        isinstance(node, Sym) and node.sentinel is not None)


_CLASS_D = frozenset(range(0x30, 0x3A))
_CLASS_W = _CLASS_D | frozenset(range(0x41, 0x5B)) | frozenset(range(0x61, 0x7B)) | {0x5F}
_CLASS_S = frozenset(b" \t\n\r\f\v")
_ALL_BYTES = frozenset(range(256))
_DOT = _ALL_BYTES - {0x0A}  # '.' excludes \n (re default, no DOTALL)

# Hard cap on AST leaf count after {m,n} expansion; the automaton state
# count equals the leaf count, and transition tables are quadratic in it
# (an unchecked quantifier nest would compile gigabyte tables). RE2
# analog: "program size too large". KLOGS_MAX_PATTERN_POSITIONS
# overrides it in BOTH directions — raise for legitimately huge
# patterns, lower to tighten VMEM bounds — and applies uniformly to the
# per-pattern cap here and the union-automaton cap in glushkov.py.
MAX_POSITIONS = 4096

# Regex features that are valid `re` but OUTSIDE this compiler's
# subset AND whose meaning depends on group NUMBERING: numbered
# backreferences, named backreferences, and conditional group
# references. They matter beyond "unsupported": when a pattern set
# falls back to the host engines, a combined alternation
# ``(?:p1)|(?:p2)`` RENUMBERS groups, silently resolving these to the
# wrong group (the PR 3 ``(?(1))`` bug — lines dropped with no error).
# ``best_host_filter`` (filters/cpu.py) builds its fallback classifier
# from THIS tuple, and the ``dispatch-parity`` static-analysis pass
# (tools/analysis) probes both sides, so the two feature tables cannot
# drift apart again. Each token is one alternation branch of the
# classifier regex.
GROUP_REF_TOKENS = (r"\\[1-9]", r"\(\?P=", r"\(\?\(")


def max_positions_cap() -> int:
    """Effective position cap (env override or MAX_POSITIONS). Read
    once per parse/build — not per leaf — by the callers."""
    from klogs_tpu.utils.env import read as env_read

    s = env_read("KLOGS_MAX_PATTERN_POSITIONS")
    if s is None:
        return MAX_POSITIONS
    try:
        return max(1, int(s))
    except ValueError:
        # Deliberately NOT RegexSyntaxError: callers treat that as "bad
        # pattern" and soft-skip (the fuzzer would pass vacuously, the
        # CLI would blame --match). A config typo should crash loudly.
        raise ValueError(
            f"KLOGS_MAX_PATTERN_POSITIONS must be an integer, got {s!r}"
        ) from None


def _casefold(s: frozenset) -> frozenset:
    out = set(s)
    for b in s:
        if 0x41 <= b <= 0x5A:
            out.add(b + 0x20)
        elif 0x61 <= b <= 0x7A:
            out.add(b - 0x20)
    return frozenset(out)


class _Parser:
    def __init__(self, pattern: str, ignore_case: bool = False) -> None:
        # Patterns arrive as str from the CLI; we match raw bytes, so
        # encode utf-8 — the same bytes RegexFilter's re.compile(p.encode())
        # sees, making byte-wise parsing here exactly equivalent to the
        # CPU baseline (a non-ASCII literal becomes its utf-8 byte
        # sequence; quantifiers bind to the final byte, as in re).
        self.src = pattern.encode("utf-8")
        self.pos = 0
        self.ignore_case = ignore_case
        self.dotall = False
        self.n_leaves = 0
        self.group_names: set[bytes] = set()
        self.max_positions = max_positions_cap()  # read once per parse

    # -- low-level cursor ------------------------------------------------
    def _peek(self) -> int | None:
        return self.src[self.pos] if self.pos < len(self.src) else None

    def _next(self) -> int:
        if self.pos >= len(self.src):
            raise RegexSyntaxError("unexpected end of pattern")
        b = self.src[self.pos]
        self.pos += 1
        return b

    def _expect(self, ch: int) -> None:
        if self._peek() != ch:
            raise RegexSyntaxError(
                f"expected {chr(ch)!r} at position {self.pos} in {self.src!r}"
            )
        self.pos += 1

    def _leaf(self, **kw: object) -> Sym:
        self.n_leaves += 1
        if self.n_leaves > self.max_positions:
            raise RegexSyntaxError(
                f"pattern too large: more than {self.max_positions} "
                "positions (KLOGS_MAX_PATTERN_POSITIONS overrides the cap)"
            )
        return Sym(**kw)

    def _sym(self, byte_set: frozenset) -> Sym:
        if self.ignore_case:
            byte_set = _casefold(byte_set)
        return self._leaf(bytes_=byte_set)

    # -- grammar ---------------------------------------------------------
    _FLAG_ATTR = {0x69: "ignore_case", 0x73: "dotall"}  # i, s

    def _skip_comments(self) -> None:
        """Splice out ``(?#...)`` comments at the cursor. Comments are
        TRANSPARENT in re's token stream — a quantifier after one binds
        to the atom BEFORE it (``a(?#c)*b`` ≡ ``a*b``) — so they are
        consumed at the lexical level, never parsed as atoms. The first
        ')' ends a comment; EOF inside one is 'unexpected end'."""
        while self.src[self.pos:self.pos + 3] == b"(?#":
            self.pos += 3
            while self._next() != 0x29:  # ')'
                pass

    def _scan_flags(self) -> "tuple[list[int], list[int]] | None":
        """At a position just past ``(?``: consume ``[is]*(-[is]+)?:``
        and return (positive, negative) flag byte lists, or None (cursor
        restored) when this is not a flags/plain group — the caller
        rejects with the group-syntax message. An unknown flag letter is
        its own loud error, named. The plain ``(?:`` form is the empty
        case. Global ``(?i)``-style prefixes are handled in parse()."""
        start = self.pos
        pos_flags: list[int] = []
        neg_flags: list[int] = []
        bucket = pos_flags
        while True:
            c = self._peek()
            if c in self._FLAG_ATTR:
                self.pos += 1
                bucket.append(c)
            elif c == 0x2D and bucket is pos_flags:  # '-'
                self.pos += 1
                bucket = neg_flags
            elif c == 0x3A:  # ':'
                self.pos += 1
                if bucket is neg_flags and not neg_flags:
                    break  # '(?-:' — not a valid flags group
                if set(pos_flags) & set(neg_flags):
                    raise RegexSyntaxError(
                        "inline flag turned on and off in the same "
                        "group, as in re")
                return pos_flags, neg_flags
            elif c is not None and chr(c).isalpha():
                raise RegexSyntaxError(
                    f"unsupported inline flag {chr(c)!r} (only i and s)")
            else:
                break
        self.pos = start
        return None

    def parse(self) -> object:
        # Whole-pattern global flags — (?i) (?s) (?si) ... — at the
        # start only, as in re ("global flags not at the start of the
        # expression" is re's error for the misplaced form, which the
        # group parser rejects loudly here too).
        self._skip_comments()
        while self.src[self.pos:self.pos + 2] == b"(?":
            saved = self.pos
            self.pos += 2
            flags: list[int] = []
            while self._peek() in self._FLAG_ATTR:
                flags.append(self._next())
            if flags and self._peek() == 0x29:  # ')'
                self.pos += 1
                for f in flags:
                    setattr(self, self._FLAG_ATTR[f], True)
                self._skip_comments()
            else:
                self.pos = saved
                break
        node = self._alt()
        if self.pos != len(self.src):
            raise RegexSyntaxError(
                f"unbalanced ')' at position {self.pos} in {self.src!r}"
            )
        return node

    def _alt(self) -> object:
        parts = [self._concat()]
        while self._peek() == 0x7C:  # '|'
            self.pos += 1
            parts.append(self._concat())
        return parts[0] if len(parts) == 1 else Alt(tuple(parts))

    def _concat(self) -> object:
        parts = []
        while True:
            self._skip_comments()
            c = self._peek()
            if c is None or c in (0x7C, 0x29):  # '|' ')'
                break
            parts.append(self._repeat())
        if not parts:
            return Epsilon()
        return parts[0] if len(parts) == 1 else Cat(tuple(parts))

    def _repeat(self) -> object:
        node = self._atom()
        seen_quant = False
        while True:
            self._skip_comments()  # a(?#c)*b ≡ a*b: * binds to a
            c = self._peek()
            if c == 0x2A:  # '*'
                self._reject_bad_repeat(node, seen_quant)
                self.pos += 1
                node = Star(node)
            elif c == 0x2B:  # '+'
                self._reject_bad_repeat(node, seen_quant)
                node = Cat((node, Star(node)))
                self.pos += 1
            elif c == 0x3F:  # '?'
                self._reject_bad_repeat(node, seen_quant)
                self.pos += 1
                node = Alt((node, Epsilon()))
            elif c == 0x7B:  # '{'
                saved = self.pos
                rep = self._try_counted()
                if rep is None:
                    self.pos = saved
                    break
                self._reject_bad_repeat(node, seen_quant)
                lo, hi = rep
                node = self._expand_counted(node, lo, hi)
            else:
                break
            seen_quant = True
            # Lazy quantifier suffix ('+?' '*?' '??' '{m,n}?'): lazy vs
            # greedy picks WHICH match, not WHETHER one exists, so for
            # any-match semantics the language is identical — consume it.
            if self._peek() == 0x3F:
                self.pos += 1
        return node

    def _reject_bad_repeat(self, node: object, seen_quant: bool) -> None:
        """A quantifier directly following a quantifier is either re's
        POSSESSIVE form ('a++', 'a{2,3}+' — atomic, no backtracking,
        can reject strings the NFA language accepts, so an NFA cannot
        express it) or re's 'multiple repeat' error ('a**', 'a+*').
        Reject both, like RE2 — silently parsing 'X{2,3}+' as
        '(X{2,3})+' produced WRONG verdicts (found by fuzzing).
        A quantified bare anchor ('^*', '$+') is re's 'nothing to
        repeat' error and is rejected for the same parity reason."""
        if seen_quant:
            raise RegexSyntaxError(
                f"stacked or possessive quantifier at position {self.pos}"
                " is not supported (possessive/atomic matching cannot be"
                " expressed by an NFA; group with (?:...) if you meant"
                " nested repetition)")
        if _is_bare_assertion(node):
            raise RegexSyntaxError(
                f"nothing to repeat at position {self.pos} (quantifier"
                " applied to an anchor or \\b assertion, as in re)")

    def _try_counted(self) -> tuple[int, int | None] | None:
        """Parse {m} {m,} {m,n} after the '{'; None if not a counted
        repeat (then '{' is a literal, matching re's behavior)."""
        self._expect(0x7B)
        digits = b""
        while self._peek() is not None and 0x30 <= self._peek() <= 0x39:
            digits += bytes([self._next()])
        if not digits:
            return None
        lo = int(digits)
        hi: int | None = lo
        if self._peek() == 0x2C:  # ','
            self.pos += 1
            digits = b""
            while self._peek() is not None and 0x30 <= self._peek() <= 0x39:
                digits += bytes([self._next()])
            hi = int(digits) if digits else None
        if self._peek() != 0x7D:  # '}'
            return None
        self.pos += 1
        if hi is not None and hi < lo:
            raise RegexSyntaxError(f"bad repeat range {{{lo},{hi}}}")
        return lo, hi

    def _expand_counted(self, node: object, lo: int, hi: int | None) -> object:
        """e{m,n} → e^m (e?)^(n-m); e{m,} → e^m e*. Leaf-count safety:
        expansion revisits the same subtree, and Glushkov assigns fresh
        positions per visit, so count leaves here too."""
        n_inner = _count_leaves(node)
        total = n_inner * (hi if hi is not None else lo + 1)
        self.n_leaves += total - n_inner  # node's own leaves already counted
        if self.n_leaves > self.max_positions:
            raise RegexSyntaxError(
                f"pattern too large: counted repeat expands past "
                f"{self.max_positions} positions "
                "(KLOGS_MAX_PATTERN_POSITIONS overrides the cap)"
            )
        parts: list = [node] * lo
        if hi is None:
            parts.append(Star(node))
        else:
            parts.extend([Alt((node, Epsilon()))] * (hi - lo))
        if not parts:
            return Epsilon()
        return parts[0] if len(parts) == 1 else Cat(tuple(parts))

    def _atom(self) -> object:
        c = self._next()
        if c == 0x28:  # '('
            saved_flags: tuple | None = None
            if self._peek() == 0x3F:  # '(?'
                self.pos += 1
                n = self._peek()
                if n == 0x50:  # 'P' — (?P<name>...): captures are
                    # irrelevant to boolean matching, so a named group
                    # is just a group; backref forms stay rejected.
                    if self.src[self.pos:self.pos + 2] != b"P<":
                        raise RegexSyntaxError(
                            "only the (?P<name>...) ?P-form is supported "
                            "(no (?P=name) backreferences)")
                    self.pos += 2
                    name = b""
                    while self._peek() not in (None, 0x3E):  # '>'
                        name += bytes([self._next()])
                    self._expect(0x3E)
                    if (not name or not name.isascii()
                            or not name.decode("ascii").isidentifier()):
                        # re (bytes patterns) additionally rejects
                        # non-ASCII names — mirror it so the CPU re
                        # baseline compiles everything we accept.
                        raise RegexSyntaxError(
                            f"bad group name {name.decode('latin-1')!r}")
                    if name in self.group_names:
                        # re errors on redefinition; accepting here would
                        # compile patterns the CPU re baseline rejects.
                        raise RegexSyntaxError(
                            f"redefinition of group name "
                            f"{name.decode('latin-1')!r}, as in re")
                    self.group_names.add(name)
                    node = self._alt()
                    self._expect(0x29)
                    if _is_bare_assertion(node):
                        node = Cat((node,))
                    return node
                flags = self._scan_flags()
                if flags is None:
                    raise RegexSyntaxError(
                        "only (?:...) and (?i/s:...) flag groups supported "
                        "(no lookaround/named groups; global flags go at "
                        "the start, as in re)"
                    )
                saved_flags = (self.ignore_case, self.dotall)
                pos_flags, neg_flags = flags
                for f in pos_flags:
                    setattr(self, self._FLAG_ATTR[f], True)
                for f in neg_flags:
                    setattr(self, self._FLAG_ATTR[f], False)
            node = self._alt()
            if saved_flags is not None:
                self.ignore_case, self.dotall = saved_flags
            self._expect(0x29)
            if _is_bare_assertion(node):
                # re's "nothing to repeat" applies to a BARE anchor or
                # assertion, not a group containing one ((?:\b)? is
                # legal); a one-part Cat defeats _reject_bad_repeat
                # without changing the language.
                node = Cat((node,))
            return node
        if c == 0x5B:  # '['
            return self._char_class()
        if c == 0x2E:  # '.'
            return self._leaf(bytes_=_ALL_BYTES if self.dotall else _DOT)
        if c == 0x5E:  # '^'
            return self._leaf(sentinel=BEGIN)
        if c == 0x24:  # '$'
            return self._leaf(sentinel=END)
        if c == 0x5C:  # '\'
            n = self._peek()
            if n == 0x62:  # \b — word boundary (backspace inside [...])
                self.pos += 1
                return Boundary(negate=False)
            if n == 0x42:  # \B
                self.pos += 1
                return Boundary(negate=True)
            if n == 0x41:  # \A — start of string; ≡ ^ here (single-line
                self.pos += 1  # bytes domain, no MULTILINE)
                return self._leaf(sentinel=BEGIN)
            if n == 0x5A:  # \Z — end of string; ≡ $ (re bytes semantics)
                self.pos += 1
                return self._leaf(sentinel=END)
            return self._sym(self._escape(in_class=False))
        if c in (0x2A, 0x2B, 0x3F):  # quantifier with nothing to repeat
            raise RegexSyntaxError(f"nothing to repeat before {chr(c)!r}")
        return self._sym(frozenset({c}))

    def _escape(self, in_class: bool) -> frozenset:
        c = self._next()
        simple = {
            0x74: 0x09, 0x6E: 0x0A, 0x72: 0x0D,  # t n r
            0x66: 0x0C, 0x76: 0x0B, 0x30: 0x00,  # f v 0
            0x61: 0x07, 0x65: 0x1B,              # a e
        }
        if c in simple:
            return frozenset({simple[c]})
        if c == 0x78:  # \xHH
            h = bytes([self._next(), self._next()])
            try:
                return frozenset({int(h, 16)})
            except ValueError:
                raise RegexSyntaxError(f"bad hex escape \\x{h.decode('latin-1')}")
        classes = {
            0x64: _CLASS_D, 0x44: _ALL_BYTES - _CLASS_D,  # d D
            0x77: _CLASS_W, 0x57: _ALL_BYTES - _CLASS_W,  # w W
            0x73: _CLASS_S, 0x53: _ALL_BYTES - _CLASS_S,  # s S
        }
        if c in classes:
            return classes[c]
        if c == 0x62:  # \b: backspace inside a class (re semantics);
            # outside a class it is intercepted in _atom as Boundary.
            if in_class:
                return frozenset({0x08})
            raise RegexSyntaxError("internal: \\b must be handled in _atom")
        if chr(c).isalnum():
            # Includes [\B]: re rejects it as a bad escape in a class.
            raise RegexSyntaxError(f"unsupported escape \\{chr(c)}")
        return frozenset({c})  # escaped punctuation

    def _char_class(self) -> Sym:
        negate = False
        if self._peek() == 0x5E:  # '^'
            negate = True
            self.pos += 1
        members: set[int] = set()
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise RegexSyntaxError("unterminated character class")
            if c == 0x5D and not first:  # ']'
                self.pos += 1
                break
            first = False
            self.pos += 1
            if c == 0x5C:
                lo_set = self._escape(in_class=True)
                if len(lo_set) != 1:
                    members |= lo_set  # \d etc. inside class: no range
                    continue
                (lo,) = lo_set
            else:
                lo = c
            if self._peek() == 0x2D and self.pos + 1 < len(self.src) and self.src[self.pos + 1] != 0x5D:
                self.pos += 1  # '-'
                hc = self._next()
                if hc == 0x5C:
                    hi_set = self._escape(in_class=True)
                    if len(hi_set) != 1:
                        raise RegexSyntaxError("bad character range endpoint")
                    (hi,) = hi_set
                else:
                    hi = hc
                if hi < lo:
                    raise RegexSyntaxError(f"bad character range {chr(lo)}-{chr(hi)}")
                members |= set(range(lo, hi + 1))
            else:
                members.add(lo)
        result = frozenset(members)
        # Casefold BEFORE negation: (?i)[^a] must exclude both 'a' and
        # 'A' (re semantics); folding after negation would re-add them.
        if self.ignore_case:
            result = _casefold(result)
        if negate:
            result = _ALL_BYTES - result
        if not result:
            raise RegexSyntaxError("empty character class matches nothing")
        return self._leaf(bytes_=result)


def _count_leaves(node: object) -> int:
    if isinstance(node, Sym):
        return 1
    if isinstance(node, (Epsilon, Boundary)):
        return 0
    if isinstance(node, (Cat, Alt)):
        return sum(_count_leaves(p) for p in node.parts)
    if isinstance(node, Star):
        return _count_leaves(node.inner)
    raise TypeError(node)


def parse(pattern: str, ignore_case: bool = False) -> object:
    """Parse one pattern into the AST. Raises RegexSyntaxError on
    unsupported or malformed syntax."""
    return _Parser(pattern, ignore_case=ignore_case).parse()
