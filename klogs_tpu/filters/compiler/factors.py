"""Mandatory literal-factor extraction: the multi-byte half of the
regex index.

The pair-CNF prefilter (prefilter.py) answers "which adjacent byte
pairs must appear"; this module answers the stronger question "which
multi-byte LITERALS must appear" — the classic literal-index idea from
"Regular Expression Indexing for Log Analysis" (PAPERS.md) and
Hyperscan's literal decomposition. A factor of ``panic:`` is worth far
more than its five constituent pairs: pairs may be scattered anywhere
in a line, a factor must occur contiguously, so a q-gram sweep over it
narrows thousands of patterns to a handful of candidates per line
(filters/compiler/index.py builds that sweep).

Extraction is structural over the parser AST. Every node summarizes to
(exact, pref, suff, factors):

- ``exact``  — the node's byte language is exactly {exact} (literals,
  and zero-width nodes as the empty string: sentinels and \\b consume
  no line bytes, so they are transparent to containment-necessity).
- ``pref``/``suff`` — mandatory literal prefix/suffix of every match.
- ``factors`` — internal literals every match must contain.

Cat concatenates prefix/suffix chains and mints the boundary literal
``a.suff + b.pref`` (contiguous by construction). Alt keeps only what
is mandatory in EVERY branch: the longest common prefix/suffix, plus
maximal common substrings of the branches' mandatory sets (a substring
of a mandatory literal is itself mandatory). Star and other nullable
or shape-unknown content contribute nothing — exactly the
conservatism that keeps the index a NECESSARY condition: a reported
factor absent from a line proves the pattern cannot match it, never
the reverse.
"""

from dataclasses import dataclass

from klogs_tpu.filters.compiler.parser import (
    Alt,
    Boundary,
    Cat,
    Epsilon,
    Star,
    Sym,
    parse,
)

# THE rarity prior (shared with clause selectivity ranking — one
# source of truth: tuning it re-ranks clauses, factor scores, and the
# sweep's window anchoring together).
from klogs_tpu.filters.compiler.prefilter import _byte_weight as _byte_rarity

# Factors shorter than this carry too little selectivity to index
# (the q-gram sweep needs >= 4 bytes; 3-byte factors still help the
# host verify step).
MIN_FACTOR_LEN = 3
# Stored-literal cap: prefixes/suffixes truncate to their outer
# MAX_FACTOR_LEN bytes (a truncation of a mandatory literal is itself
# mandatory), bounding work on pathological literal walls.
MAX_FACTOR_LEN = 24
# An exact literal longer than this demotes to pref/suff form.
_EXACT_CAP = 64
MAX_FACTORS_PER_PATTERN = 4


@dataclass(frozen=True)
class _FSum:
    """Factor summary of one AST node (see module docstring)."""

    exact: "bytes | None"
    pref: bytes = b""
    suff: bytes = b""
    factors: frozenset = frozenset()


_EMPTY = _FSum(exact=b"")
_UNKNOWN = _FSum(exact=None)


def factor_score(f: bytes) -> float:
    """Ranking key: smaller = more selective. Length dominates (every
    extra byte multiplies selectivity), rarity breaks ties."""
    rarity = sum(_byte_rarity(b) for b in f) / max(1, len(f))
    return -float(len(f)) * 8.0 + rarity


def _trunc_pref(s: bytes) -> bytes:
    return s[:MAX_FACTOR_LEN]


def _trunc_suff(s: bytes) -> bytes:
    return s[-MAX_FACTOR_LEN:] if len(s) > MAX_FACTOR_LEN else s


def _demote(s: _FSum) -> _FSum:
    """Exact literal grown past the cap -> pref/suff form."""
    if s.exact is None or len(s.exact) <= _EXACT_CAP:
        return s
    return _FSum(exact=None, pref=_trunc_pref(s.exact),
                 suff=_trunc_suff(s.exact),
                 factors=frozenset({s.exact[:MAX_FACTOR_LEN]}))


def _cat2(a: _FSum, b: _FSum) -> _FSum:
    if a.exact is not None and b.exact is not None:
        return _demote(_FSum(exact=a.exact + b.exact))
    a_suff = a.exact if a.exact is not None else a.suff
    b_pref = b.exact if b.exact is not None else b.pref
    pref = _trunc_pref(a.exact + b.pref) if a.exact is not None else a.pref
    suff = _trunc_suff(a.suff + b.exact) if b.exact is not None else b.suff
    factors = set(a.factors) | set(b.factors)
    mid = _trunc_suff(a_suff) + _trunc_pref(b_pref)
    if mid:
        factors.add(_trunc_pref(mid) if len(mid) > MAX_FACTOR_LEN else mid)
    return _FSum(exact=None, pref=pref, suff=suff,
                 factors=frozenset(factors))


def _mandatory_set(s: _FSum) -> frozenset:
    """Every literal the summary proves mandatory (empties dropped)."""
    out = set(s.factors)
    if s.exact is not None:
        out.add(s.exact)
    else:
        out.add(s.pref)
        out.add(s.suff)
    out.discard(b"")
    return frozenset(out)


def _common_pref(items: "list[bytes]") -> bytes:
    out = items[0]
    for s in items[1:]:
        n = 0
        for x, y in zip(out, s):
            if x != y:
                break
            n += 1
        out = out[:n]
    return out


def _alt(subs: "list[_FSum]") -> _FSum:
    exacts = [s.exact for s in subs]
    if all(e is not None and e == exacts[0] for e in exacts):
        return subs[0]
    prefs = [s.exact if s.exact is not None else s.pref for s in subs]
    suffs = [s.exact if s.exact is not None else s.suff for s in subs]
    pref = _common_pref(prefs)
    suff = _common_pref([s[::-1] for s in suffs])[::-1]
    # Common substrings: s is mandatory for the Alt iff every branch
    # has a mandatory literal containing s. Enumerate branch-0 substrings
    # (bounded: literals are <= MAX_FACTOR_LEN), keep the maximal ones.
    sets = [_mandatory_set(s) for s in subs]
    common: set[bytes] = set()
    if all(sets):
        cands: set[bytes] = set()
        for f in sets[0]:
            for i in range(len(f)):
                for j in range(i + MIN_FACTOR_LEN, len(f) + 1):
                    cands.add(f[i:j])
        for c in cands:
            if all(any(c in f for f in fs) for fs in sets[1:]):
                common.add(c)
        common = {c for c in common
                  if not any(c != d and c in d for d in common)}
    return _FSum(exact=None, pref=pref, suff=suff,
                 factors=frozenset(common))


def _summarize(node: object) -> _FSum:
    if isinstance(node, (Epsilon, Boundary)):
        return _EMPTY
    if isinstance(node, Sym):
        if node.sentinel is not None:
            return _EMPTY  # zero line bytes: transparent
        if len(node.bytes_) == 1:
            return _FSum(exact=bytes([next(iter(node.bytes_))]))
        return _UNKNOWN
    if isinstance(node, Star):
        return _FSum(exact=None)  # zero iterations: nothing mandatory
    if isinstance(node, Cat):
        acc = _EMPTY
        for part in node.parts:
            acc = _cat2(acc, _summarize(part))
        return acc
    if isinstance(node, Alt):
        return _alt([_summarize(p) for p in node.parts])
    raise TypeError(node)


def factors_from_ast(node: object) -> "list[bytes]":
    """Mandatory literal factors of a parsed pattern, most selective
    first, capped at MAX_FACTORS_PER_PATTERN, each >= MIN_FACTOR_LEN.
    Overlapping/substring-redundant entries are pruned."""
    s = _summarize(node)
    cands = sorted((f for f in _mandatory_set(s)
                    if len(f) >= MIN_FACTOR_LEN), key=factor_score)
    out: "list[bytes]" = []
    for f in cands:
        if any(f in kept for kept in out):
            continue  # substring of a stronger kept factor: redundant
        out.append(f)
        if len(out) >= MAX_FACTORS_PER_PATTERN:
            break
    return out


# An OR-guard wider than this matches too many lines to pay for its
# sweep entries; the pattern stays unindexed (always-candidate).
MAX_GUARD_FACTORS = 8

# Bounded class enumeration: a run of small byte classes has a small
# finite language ("5[12]\d " is 20 four-byte literals), and that
# language is an OR-guard — every match contains exactly one member.
# Without it, any class inside the literal chain breaks extraction and
# the pattern degrades to always-candidate even when its language is
# nearly literal. Enumerated guards may exceed MAX_GUARD_FACTORS (that
# cap prices human-written alternations, whose branches are broad);
# enumerated members are same-length siblings differing in one class
# byte, so the family is as rare as its rarest member times the class
# size — priced by _ENUM_GUARD_MAX instead.
_ENUM_SET_MAX = 16    # widest byte class worth enumerating
_ENUM_GUARD_MAX = 32  # total literals per enumerated family
_ENUM_MIN_LEN = 4     # one full narrow probe window (index.NARROW)


def _enum_lits(node: object) -> "list[bytes] | None":
    """The node's full byte language when finite and small, else None.
    Zero-width nodes contribute the empty string (transparent)."""
    if isinstance(node, (Epsilon, Boundary)):
        return [b""]
    if isinstance(node, Sym):
        if node.sentinel is not None:
            return [b""]
        if len(node.bytes_) > _ENUM_SET_MAX:
            return None
        return [bytes([b]) for b in sorted(node.bytes_)]
    if isinstance(node, Cat):
        acc = [b""]
        for part in node.parts:
            sub = _enum_lits(part)
            if sub is None:
                return None
            acc = [a + s for a in acc for s in sub]
            if len(acc) > _ENUM_GUARD_MAX:
                return None
        return acc
    if isinstance(node, Alt):
        out: "list[bytes]" = []
        for part in node.parts:
            sub = _enum_lits(part)
            if sub is None:
                return None
            out.extend(sub)
            if len(out) > _ENUM_GUARD_MAX:
                return None
        return out
    return None  # Star / unknown: unbounded or nullable


def _enum_guard(parts: "list[object]", banned: "object | None"
                ) -> "list[bytes] | None":
    """Best enumerated OR-guard over contiguous runs of enumerable
    parts. A match of the Cat contains a match of parts[i:j]
    consecutively, hence contains one member of that run's (finite)
    language — so each run's literal set is a valid OR-guard; the
    best-scored one wins."""
    best: "list[bytes] | None" = None
    best_score = 0.0
    for i in range(len(parts)):
        lits = [b""]
        for part in parts[i:]:
            sub = _enum_lits(part)
            if sub is None:
                break
            nxt = [a + s for a in lits for s in sub]
            if len(nxt) > _ENUM_GUARD_MAX:
                break
            lits = nxt
            fam = [_trunc_pref(f) for f in lits]
            if (any(len(f) < _ENUM_MIN_LEN for f in fam)
                    or len(set(fam)) != len(fam)
                    or (banned is not None and any(banned(f)
                                                   for f in fam))):
                continue
            score = max(factor_score(f) for f in fam)
            if best is None or score < best_score:
                best, best_score = fam, score
    return best


def guard_factors(node: object,
                  banned: "object | None" = None
                  ) -> "list[bytes] | None":
    """OR-semantics guard for the regex index: a set of literals such
    that EVERY match of the pattern contains AT LEAST ONE of them.

    A pattern with a mandatory factor guards on its rarest one
    (singleton OR-set). A pattern that is an alternation with no
    common factor — ``FATAL|CRIT`` — still guards: every match matches
    some branch, so the union of per-branch guards is necessary. A
    concatenation whose own factor chain yields nothing usable still
    guards through any one guardable PART — a match of the Cat
    contains a match of every part, so a part's guard is necessary for
    the whole; the best-scored part guard wins.
    Returns None when no guard exists (nullable content everywhere, or
    an alternation with an unguardable branch): the pattern must stay
    an always-candidate.

    ``banned`` (optional predicate ``bytes -> bool``) vetoes guard
    literals the caller has measured to be useless on the live corpus
    — a factor present in ~every line narrows nothing while taxing
    every sweep position (the IndexedFilter's adaptive re-guard;
    docs/PATTERNS.md). Banning only restricts the CHOICE of guard:
    whatever survives is still a necessary condition, and a pattern
    with no unbanned guard degrades to always-candidate — necessity is
    preserved under any ban."""
    fs = [f for f in factors_from_ast(node)
          if banned is None or not banned(f)]
    if fs:
        return [fs[0]]
    if isinstance(node, Alt):
        out: "list[bytes]" = []
        for part in node.parts:
            sub = guard_factors(part, banned)
            if sub is None:
                return None
            for f in sub:
                if f not in out:
                    out.append(f)
            if len(out) > MAX_GUARD_FACTORS:
                return None
        return out
    if isinstance(node, Cat):
        best: "list[bytes] | None" = None
        best_score = 0.0
        for part in node.parts:
            sub = guard_factors(part, banned)
            if sub is None:
                continue
            # An OR-set is as selective as its WORST member.
            score = max(factor_score(f) for f in sub)
            if best is None or score < best_score:
                best, best_score = sub, score
        enum = _enum_guard(list(node.parts), banned)
        if enum is not None:
            score = max(factor_score(f) for f in enum)
            if best is None or score < best_score:
                best = enum
        return best
    return None


def mandatory_factors(pattern: str, ignore_case: bool = False
                      ) -> "list[bytes]":
    """Parse + extract. Case-insensitive patterns casefold their byte
    sets in the parser, so cased letters become 2-byte sets and drop
    out of the literal chain — such patterns simply yield fewer (often
    zero) factors and lean on the pair-CNF index instead."""
    return factors_from_ast(parse(pattern, ignore_case=ignore_case))
