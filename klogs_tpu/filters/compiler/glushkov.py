"""Glushkov position automaton → dense arrays for the TPU engine.

Why Glushkov (and not Thompson/DFA): the position automaton has no
epsilon transitions and the *defining* property that every state is
entered only on its own symbol class. The whole per-character update
therefore factors into a character-independent reachability step and a
character-dependent mask:

    v' = (reachable-from(v) | inject) & B[class(c)]

With states packed along the 128-lane axis, ``reachable-from`` is a
0/1 matmul ``v @ F`` on the MXU and ``B[class(c)]`` a tiny gather (or
one-hot matmul) — exactly the shape TPUs like. A DFA would need
data-dependent table walks (serial, gather-bound); Thompson NFAs need
epsilon closure. See SURVEY.md §2 "Pattern compiler" row.

Anchors arrive from the parser as BEGIN/END sentinel symbols; the
engine feeds a virtual BEGIN before byte 0 and END after the last
byte, so ^/$ need no special-casing here and nullability of the
symbol-regex is exactly "matches every line" (match_all).

Word-boundary assertions (\\b/\\B) also compile to static structure,
with zero runtime cost: every pair of consecutively consumed symbols
has one adjacency relation (word-categories equal / differ / the
BEGIN→END empty-line pair), an assertion is a constraint on the
relation, and constraints intersect through sequencing and union
through alternation. Mid-pattern assertions filter follow edges (over
category-pure, pre-split positions); leading ones route injection
through always-injected context positions that track the previous
symbol's category; trailing ones route acceptance through
boundary-check positions that consume the next symbol. See
compile_patterns for the wiring and the interpreter-probed empty-line
rule.

Byte-class compression: bytes with identical membership across all
position symbol-sets collapse to one class, so the character-mask
table is [n_classes, S] with n_classes typically ≪ 256.
"""

from dataclasses import dataclass

import numpy as np

from klogs_tpu.filters.compiler.parser import (
    BEGIN,
    END,
    _ALL_BYTES,
    _CLASS_W,
    Alt,
    Boundary,
    Cat,
    Epsilon,
    RegexSyntaxError,
    Star,
    Sym,
    max_positions_cap,
    parse,
)

# The union-automaton position cap equals the parser's per-pattern cap
# (parser.MAX_POSITIONS, overridden by the same
# KLOGS_MAX_PATTERN_POSITIONS knob, read once per _Builder) so raising
# or tightening one cap never leaves the other silently binding.


@dataclass
class NFAProgram:
    """Dense automaton arrays, ready to pad + ship to the engine.

    Class-id layout: 0..n_byte_classes-1 are byte classes (byte_class
    maps each of the 256 byte values to one), then begin_class,
    end_class, pad_class. pad_class has an all-zero row in char_mask so
    padded tail positions kill all states while sticky `matched` holds.
    """

    n_states: int
    n_classes: int
    byte_class: np.ndarray  # [256] int32
    begin_class: int
    end_class: int
    pad_class: int
    char_mask: np.ndarray  # [n_classes, n_states] bool — B table
    follow: np.ndarray  # [n_states, n_states] bool — F[i,j]: j in follow(i)
    inject: np.ndarray  # [n_states] bool — firstpos(root), injected each step
    accept: np.ndarray  # [n_states] bool — lastpos(root)
    match_all: bool  # symbol-regex nullable → empty match everywhere
    patterns: tuple  # the source pattern strings, for repr/debug


# Adjacency-relation bitmask (word-boundary assertions): every pair of
# consecutively consumed symbols has exactly one relation, and a
# constraint is the set of relations it admits. Sentinels count as
# non-word (re's edge-of-string rule) — EXCEPT the BEGIN→END adjacency
# (the empty line), which gets its own relation because re 3.12 lets
# neither \b nor \B match the empty string while unconstrained empty
# matches (Epsilon) of course do. Constraints compose by intersection
# (sequencing) and union (alternation); no special cases.
_EQ = 1  # categories equal          (what \B demands)
_NEQ = 2  # categories differ        (what \b demands)
_EMPTY = 4  # the BEGIN→END adjacency (the empty line)
_FULL = 7  # unconstrained

# Whether the assertions admit the empty-line adjacency is
# INTERPRETER-dependent: Python 3.12 made re.search(rb"\B", b"") not
# match (and 3.14 reverts it, gh-124130). The running `re` is both the
# property-test oracle and the production CPU baseline, so probe it
# once and encode whatever it does — the compiled engine then agrees
# with it on every interpreter version.
import re as _re

_B_NULLS = _NEQ | (_EMPTY if _re.search(rb"\b", b"") else 0)
_NB_NULLS = _EQ | (_EMPTY if _re.search(rb"\B", b"") else 0)


class _Builder:
    def __init__(self) -> None:
        self.symbols: list[object] = []  # per position: frozenset | BEGIN | END
        self.follow: list[set[int]] = []
        self.max_union = max_positions_cap()  # read once per build
        # Structural anchor-after-anchor adjacencies (divergent vs re's
        # idempotent assertions) — recorded even when a boundary
        # constraint would drop the edge, because re still matches e.g.
        # ``^\b^`` on a word-initial line while the sentinel stream
        # cannot provide BEGIN twice.
        self.divergent: list[int] = []  # position i of the earlier anchor

    def new_pos(self, symbol: object) -> int:
        if len(self.symbols) >= self.max_union:
            raise RegexSyntaxError(
                f"pattern set too large: more than "
                f"{self.max_union} total positions "
                "(KLOGS_MAX_PATTERN_POSITIONS overrides the cap)"
            )
        self.symbols.append(symbol)
        self.follow.append(set())
        return len(self.symbols) - 1

    def cat(self, i: int) -> int:
        """Word-category of position i's symbol: 1 word, 0 non-word.
        Only consulted on constrained edges, whose endpoints are
        category-pure by the _split_mixed_syms pre-pass."""
        s = self.symbols[i]
        if s is BEGIN or s is END:
            return 0
        if s <= _CLASS_W:
            return 1
        if not (s & _CLASS_W):
            return 0
        raise AssertionError(
            "mixed word/non-word position on a boundary-constrained "
            "edge — _split_mixed_syms must run on boundary patterns")

    def edge(self, i: int, j: int, cons: int) -> None:
        """Add follow edge i→j if the adjacency constraint admits the
        two symbols' categories."""
        si, sj = self.symbols[i], self.symbols[j]
        if (si is BEGIN or si is END) and (
                sj is BEGIN or (si is END and sj is END)):
            # Anchor directly (or across zero-width/optional content)
            # after another anchor: re's idempotent assertions diverge
            # from one-sentinel-per-line symbols (^^, $$, $^, ^\b^).
            # An ordinary symbol before BEGIN (a^b) stays materialized:
            # BEGIN's class never recurs, so it matches nothing, which
            # is re's behavior too.
            self.divergent.append(i)
            return
        if cons == _FULL:
            self.follow[i].add(j)
            return
        if not cons:
            return
        if si is BEGIN and sj is END:
            rel = _EMPTY  # the empty line: ^$ keeps it, ^\b?$ etc. do not
        else:
            rel = _EQ if self.cat(i) == self.cat(j) else _NEQ
        if rel & cons:
            self.follow[i].add(j)

    def visit(self, node: object) -> tuple[int, list, list]:
        """Returns (nulls, first, last).

        ``nulls``: _EQ|_NEQ|_EMPTY bits — the set of adjacency
        relations under which the node matches empty (_FULL for an
        unconditional empty match).
        ``first``/``last``: lists of (position, entry/exit constraint
        bits) — the constraint an edge into/out of the subexpression
        must satisfy (from boundary assertions at its rim). Fresh
        positions are allocated per *visit*, so subtrees shared by
        counted-repeat expansion linearize correctly."""
        if isinstance(node, Epsilon):
            return _FULL, [], []
        if isinstance(node, Boundary):
            return _NB_NULLS if node.negate else _B_NULLS, [], []
        if isinstance(node, Sym):
            p = self.new_pos(node.sentinel if node.sentinel else node.bytes_)
            return 0, [(p, _FULL)], [(p, _FULL)]
        if isinstance(node, Star):
            _, first, last = self.visit(node.inner)
            for i, ti in last:
                for j, tj in first:
                    self.edge(i, j, ti & tj)
            # Zero iterations: unconditional empty. (Assertion-only
            # iterations never ADD matches — skipping them is always
            # at least as permissive.)
            return _FULL, first, last
        if isinstance(node, Alt):
            nulls, first, last = 0, [], []
            for part in node.parts:
                n, f, la = self.visit(part)
                nulls |= n
                first += f
                last += la
            return nulls, first, last
        if isinstance(node, Cat):
            nulls, first, last = _FULL, [], []
            for part in node.parts:
                n, f, la = self.visit(part)
                for i, ti in last:
                    for j, tj in f:
                        self.edge(i, j, ti & tj)
                if nulls:  # prefix nullable: its bits constrain entry
                    first += [(j, tj & nulls) for j, tj in f if tj & nulls]
                if n:  # part nullable: its bits constrain earlier exits
                    last = la + [(i, ti & n) for i, ti in last if ti & n]
                else:
                    last = la
                # Empty match of the whole Cat: both sides empty on the
                # SAME adjacency — intersect.
                nulls &= n
            return nulls, first, last
        raise TypeError(f"unknown AST node {node!r}")


_DIVERGENT_ANCHOR_MSG = (
    "consecutive anchors (with only optional or zero-width content "
    "between) in {pat!r} are not supported: the engine consumes one "
    "BEGIN/END sentinel per line, so re's idempotent-assertion "
    "semantics cannot be honored"
)


def _contains_boundary(node: object) -> bool:
    if isinstance(node, Boundary):
        return True
    if isinstance(node, (Cat, Alt)):
        return any(_contains_boundary(p) for p in node.parts)
    if isinstance(node, Star):
        return _contains_boundary(node.inner)
    return False


def _split_mixed_syms(node: object) -> object:
    """Rewrite Syms whose byte set mixes word and non-word bytes into an
    Alt of the two pure halves, so every position has a definite
    word-category for boundary-edge filtering. Run only on patterns
    that contain \\b/\\B (costs up to 2x positions)."""
    if isinstance(node, Sym):
        if node.sentinel is not None:
            return node
        w = node.bytes_ & _CLASS_W
        nw = node.bytes_ - _CLASS_W
        if w and nw:
            return Alt((Sym(bytes_=w), Sym(bytes_=nw)))
        return node
    if isinstance(node, Cat):
        return Cat(tuple(_split_mixed_syms(p) for p in node.parts))
    if isinstance(node, Alt):
        return Alt(tuple(_split_mixed_syms(p) for p in node.parts))
    if isinstance(node, Star):
        return Star(_split_mixed_syms(node.inner))
    return node


def compile_patterns(patterns: list[str], ignore_case: bool = False) -> NFAProgram:
    """Compile K patterns into one union automaton (any-match
    semantics, ≙ RegexFilter's any(p.search(line))).

    Word-boundary assertions compile to STATIC structure — no runtime
    cost: mid-pattern \\b/\\B filter follow edges by the (category-pure,
    pre-split) endpoint categories; a leading assertion routes injection
    through always-injected context positions (active exactly when the
    previously consumed symbol had the matching category — BEGIN counts
    non-word); a trailing assertion routes acceptance through
    boundary-check positions that consume the NEXT symbol (END counts
    non-word). A pattern matching empty only AT a boundary (``\\b``,
    ``\\B``) wires context→check edges per adjacency relation, with the
    BEGIN→END pair excluded to mirror re's "\\B never matches the empty
    string" rule (Python 3.12 semantics, verified empirically)."""
    if not patterns:
        raise ValueError("compile_patterns needs at least one pattern")
    b = _Builder()
    inject: set[int] = set()
    accept: set[int] = set()
    begin_members: set[int] = set()  # extra positions in mask[BEGIN]
    end_members: set[int] = set()  # extra positions in mask[END]
    match_all = False

    # Lazily created special positions, shared across the union.
    # Context (always injected; exactly one active after every step):
    #   ctx[0] after BEGIN, ctx[1] after a non-word byte, ctx[2] after a
    #   word byte. Boundary-check accepts: bnd[0] consumes END, bnd[1] a
    #   non-word byte, bnd[2] a word byte.
    _NW = _ALL_BYTES - _CLASS_W
    specials: dict = {}

    def special(kind: str) -> int:
        p = specials.get(kind)
        if p is None:
            byte_set = {"ctx_begin": frozenset(), "ctx_nw": _NW,
                        "ctx_w": _CLASS_W, "bnd_end": frozenset(),
                        "bnd_nw": _NW, "bnd_w": _CLASS_W}[kind]
            p = specials[kind] = b.new_pos(byte_set)
            if kind.startswith("ctx"):
                inject.add(p)
                if kind == "ctx_begin":
                    begin_members.add(p)
            else:
                accept.add(p)
                if kind == "bnd_end":
                    end_members.add(p)
        return p

    def ctx_kinds(cat: int, target_is_end: bool, tag: int) -> list[str]:
        # Context kinds active when the PREVIOUS symbol had category
        # `cat`. The (ctx_begin, END-consuming target) pair IS the
        # empty-line adjacency, so it is included only when the
        # constraint admits _EMPTY (interpreter-probed; e.g. ^\B must
        # not match "" on re 3.12).
        if cat:
            return ["ctx_w"]
        if target_is_end and not tag & _EMPTY:
            return ["ctx_nw"]
        return ["ctx_begin", "ctx_nw"]

    def bnd_kinds(cat: int, source_is_begin: bool, tag: int) -> list[str]:
        # Boundary-check kinds consuming a NEXT symbol of category
        # `cat`; the (BEGIN source, bnd_end) pair is the empty-line
        # adjacency — same _EMPTY gate.
        if cat:
            return ["bnd_w"]
        if source_is_begin and not tag & _EMPTY:
            return ["bnd_nw"]
        return ["bnd_end", "bnd_nw"]

    for pat in patterns:
        ast = parse(pat, ignore_case=ignore_case)
        if _contains_boundary(ast):
            ast = _split_mixed_syms(ast)
        n0 = len(b.symbols)
        d0 = len(b.divergent)
        nulls, first, last = b.visit(ast)
        if len(b.divergent) > d0:
            raise RegexSyntaxError(_DIVERGENT_ANCHOR_MSG.format(pat=pat))
        match_all |= nulls == _FULL

        for j, tag in first:
            if tag == _FULL:
                inject.add(j)
                continue
            if b.symbols[j] is BEGIN:
                raise RegexSyntaxError(
                    f"word-boundary assertion before ^ in {pat!r} is not "
                    "supported (nothing precedes the BEGIN sentinel to "
                    "check the boundary against)")
            cj = b.cat(j)
            for c in (0, 1):  # category of the preceding symbol
                rel = _EQ if c == cj else _NEQ
                if rel & tag:
                    for k in ctx_kinds(c, b.symbols[j] is END, tag):
                        b.follow[special(k)].add(j)
        for i, tag in last:
            if tag == _FULL:
                accept.add(i)
                continue
            if b.symbols[i] is END:
                raise RegexSyntaxError(
                    f"word-boundary assertion after $ in {pat!r} is not "
                    "supported (nothing follows the END sentinel to "
                    "check the boundary against)")
            ci = b.cat(i)
            for c in (0, 1):  # category of the next symbol
                rel = _EQ if c == ci else _NEQ
                if rel & tag:
                    for k in bnd_kinds(c, b.symbols[i] is BEGIN, tag):
                        b.follow[i].add(special(k))
        if nulls != _FULL and nulls & (_EQ | _NEQ):
            # Empty match only AT a boundary/non-boundary adjacency
            # (standalone \b / \B): context→check edges for every
            # admitted (prev, next) category pair. The
            # ctx_begin→bnd_end pair is the empty-line adjacency and
            # follows the probed _EMPTY bit.
            for cp in ("ctx_begin", "ctx_nw", "ctx_w"):
                for cn in ("bnd_end", "bnd_nw", "bnd_w"):
                    if cp == "ctx_begin" and cn == "bnd_end":
                        rel = _EMPTY
                    else:
                        rel = (_EQ if (cp == "ctx_w") == (cn == "bnd_w")
                               else _NEQ)
                    if rel & nulls:
                        b.follow[special(cp)].add(special(cn))

    n = len(b.symbols)
    if n == 0:
        # Every pattern was pure-epsilon (e.g. "" or "()"): match-all
        # with a single dead state so array shapes stay non-degenerate.
        n = 1
        b.symbols.append(frozenset())
        b.follow.append(set())

    # --- byte-class compression -------------------------------------
    byte_sets = [s for s in b.symbols if isinstance(s, frozenset)]
    sig = np.zeros((256, len(byte_sets)), dtype=bool)
    for j, s in enumerate(byte_sets):
        for byte in s:
            sig[byte, j] = True
    _, byte_class = np.unique(sig, axis=0, return_inverse=True)
    byte_class = byte_class.astype(np.int32)
    n_byte_classes = int(byte_class.max()) + 1 if len(byte_sets) else 1
    begin_class = n_byte_classes
    end_class = n_byte_classes + 1
    pad_class = n_byte_classes + 2
    n_classes = n_byte_classes + 3

    char_mask = np.zeros((n_classes, n), dtype=bool)
    # One representative byte per class is enough: membership is
    # constant within a class by construction.
    rep_byte = np.zeros(n_byte_classes, dtype=np.int32)
    rep_byte[byte_class] = np.arange(256, dtype=np.int32)
    for s_idx, sym in enumerate(b.symbols):
        if sym == BEGIN:
            char_mask[begin_class, s_idx] = True
        elif sym == END:
            char_mask[end_class, s_idx] = True
        else:
            for c in range(n_byte_classes):
                if int(rep_byte[c]) in sym:
                    char_mask[c, s_idx] = True
    # Boundary machinery: ctx_begin is active after the BEGIN step,
    # bnd_end consumes the END sentinel (both also/only via these rows).
    for s_idx in begin_members:
        char_mask[begin_class, s_idx] = True
    for s_idx in end_members:
        char_mask[end_class, s_idx] = True

    follow = np.zeros((n, n), dtype=bool)
    for i, js in enumerate(b.follow):
        for j in js:
            follow[i, j] = True

    inject_v = np.zeros(n, dtype=bool)
    inject_v[list(inject)] = True
    accept_v = np.zeros(n, dtype=bool)
    accept_v[list(accept)] = True

    return NFAProgram(
        n_states=n,
        n_classes=n_classes,
        byte_class=byte_class,
        begin_class=begin_class,
        end_class=end_class,
        pad_class=pad_class,
        char_mask=char_mask,
        follow=follow,
        inject=inject_v,
        accept=accept_v,
        match_all=match_all,
        patterns=tuple(patterns),
    )


def reference_match(prog: NFAProgram, line: bytes) -> bool:
    """Pure-numpy oracle-shaped simulation of the exact update the
    TPU engine runs — used by property tests to separate 'automaton is
    wrong' from 'engine is wrong'."""
    if prog.match_all:
        return True
    classes = (
        [prog.begin_class]
        + [int(prog.byte_class[c]) for c in line]
        + [prog.end_class]
    )
    v = np.zeros(prog.n_states, dtype=bool)
    follow_u8 = prog.follow.astype(np.uint8)
    for c in classes:
        reach = (v.astype(np.uint8) @ follow_u8) > 0
        v = (reach | prog.inject) & prog.char_mask[c]
        if (v & prog.accept).any():
            return True
    return False
