"""Glushkov position automaton → dense arrays for the TPU engine.

Why Glushkov (and not Thompson/DFA): the position automaton has no
epsilon transitions and the *defining* property that every state is
entered only on its own symbol class. The whole per-character update
therefore factors into a character-independent reachability step and a
character-dependent mask:

    v' = (reachable-from(v) | inject) & B[class(c)]

With states packed along the 128-lane axis, ``reachable-from`` is a
0/1 matmul ``v @ F`` on the MXU and ``B[class(c)]`` a tiny gather (or
one-hot matmul) — exactly the shape TPUs like. A DFA would need
data-dependent table walks (serial, gather-bound); Thompson NFAs need
epsilon closure. See SURVEY.md §2 "Pattern compiler" row.

Anchors arrive from the parser as BEGIN/END sentinel symbols; the
engine feeds a virtual BEGIN before byte 0 and END after the last
byte, so ^/$ need no special-casing here and nullability of the
symbol-regex is exactly "matches every line" (match_all).

Byte-class compression: bytes with identical membership across all
position symbol-sets collapse to one class, so the character-mask
table is [n_classes, S] with n_classes typically ≪ 256.
"""

from dataclasses import dataclass

import numpy as np

from klogs_tpu.filters.compiler.parser import (
    BEGIN,
    END,
    Alt,
    Cat,
    Epsilon,
    RegexSyntaxError,
    Star,
    Sym,
    max_positions_cap,
    parse,
)

# The union-automaton position cap equals the parser's per-pattern cap
# (parser.MAX_POSITIONS, overridden by the same
# KLOGS_MAX_PATTERN_POSITIONS knob, read once per _Builder) so raising
# or tightening one cap never leaves the other silently binding.


@dataclass
class NFAProgram:
    """Dense automaton arrays, ready to pad + ship to the engine.

    Class-id layout: 0..n_byte_classes-1 are byte classes (byte_class
    maps each of the 256 byte values to one), then begin_class,
    end_class, pad_class. pad_class has an all-zero row in char_mask so
    padded tail positions kill all states while sticky `matched` holds.
    """

    n_states: int
    n_classes: int
    byte_class: np.ndarray  # [256] int32
    begin_class: int
    end_class: int
    pad_class: int
    char_mask: np.ndarray  # [n_classes, n_states] bool — B table
    follow: np.ndarray  # [n_states, n_states] bool — F[i,j]: j in follow(i)
    inject: np.ndarray  # [n_states] bool — firstpos(root), injected each step
    accept: np.ndarray  # [n_states] bool — lastpos(root)
    match_all: bool  # symbol-regex nullable → empty match everywhere
    patterns: tuple  # the source pattern strings, for repr/debug


class _Builder:
    def __init__(self) -> None:
        self.symbols: list[object] = []  # per position: frozenset | BEGIN | END
        self.follow: list[set[int]] = []
        self.max_union = max_positions_cap()  # read once per build

    def new_pos(self, symbol: object) -> int:
        if len(self.symbols) >= self.max_union:
            raise RegexSyntaxError(
                f"pattern set too large: more than "
                f"{self.max_union} total positions "
                "(KLOGS_MAX_PATTERN_POSITIONS overrides the cap)"
            )
        self.symbols.append(symbol)
        self.follow.append(set())
        return len(self.symbols) - 1

    def visit(self, node: object) -> tuple[bool, list[int], list[int]]:
        """Returns (nullable, firstpos, lastpos). Fresh positions are
        allocated per *visit*, so subtrees shared by counted-repeat
        expansion linearize correctly."""
        if isinstance(node, Epsilon):
            return True, [], []
        if isinstance(node, Sym):
            p = self.new_pos(node.sentinel if node.sentinel else node.bytes_)
            return False, [p], [p]
        if isinstance(node, Star):
            nullable, first, last = self.visit(node.inner)
            for i in last:
                self.follow[i].update(first)
            return True, first, last
        if isinstance(node, Alt):
            nullable, first, last = False, [], []
            for part in node.parts:
                n, f, l = self.visit(part)
                nullable |= n
                first += f
                last += l
            return nullable, first, last
        if isinstance(node, Cat):
            nullable, first, last = True, [], []
            for part in node.parts:
                n, f, l = self.visit(part)
                for i in last:
                    self.follow[i].update(f)
                if nullable:
                    first += f
                if n:
                    last += l
                else:
                    last = l
                nullable &= n
            return nullable, first, last
        raise TypeError(f"unknown AST node {node!r}")


def _reject_divergent_anchor_pairs(b: "_Builder", n0: int, pat: str) -> None:
    """Reject patterns where anchor-as-symbol semantics diverge from
    re's anchor-as-assertion semantics (fuzz find, 2026-07-30).

    The engine feeds ONE virtual BEGIN and ONE END sentinel per line, so
    an anchor symbol can be consumed once. re treats anchors as
    idempotent zero-width assertions: ``^^`` matches at position 0,
    ``$$`` at the end, ``$^`` on an empty string — all unmatchable here.
    The divergent cases are exactly an anchor position reachable
    immediately (or across nullable-only content, which Glushkov follow
    already short-circuits) after another anchor position, except
    BEGIN→END (``^$``: the sentinel stream really does provide BEGIN
    then END, so it matches the empty line in both semantics). Adjacent
    same-anchor pairs could be merged soundly, but ``$^`` cannot, and a
    loud reject keeps the oracle contract simple: every ACCEPTED pattern
    behaves exactly like re. (Cf. the possessive-quantifier and \\b
    rejections — RE2-style subset, documented in the parser.)"""
    for i in range(n0, len(b.symbols)):
        si = b.symbols[i]
        if si is not BEGIN and si is not END:
            continue
        for j in b.follow[i]:
            sj = b.symbols[j]
            if sj is BEGIN or (si is END and sj is END):
                raise RegexSyntaxError(
                    f"consecutive anchors ({'^' if si is BEGIN else '$'}"
                    f"...{'^' if sj is BEGIN else '$'} with only optional "
                    f"content between) in {pat!r} are not supported: the "
                    "engine consumes one BEGIN/END sentinel per line, so "
                    "re's idempotent-assertion semantics cannot be honored"
                )


def compile_patterns(patterns: list[str], ignore_case: bool = False) -> NFAProgram:
    """Compile K patterns into one union automaton (any-match
    semantics, ≙ RegexFilter's any(p.search(line)))."""
    if not patterns:
        raise ValueError("compile_patterns needs at least one pattern")
    b = _Builder()
    inject: set[int] = set()
    accept: set[int] = set()
    match_all = False
    for pat in patterns:
        n0 = len(b.symbols)
        nullable, first, last = b.visit(parse(pat, ignore_case=ignore_case))
        match_all |= nullable
        inject.update(first)
        accept.update(last)
        _reject_divergent_anchor_pairs(b, n0, pat)

    n = len(b.symbols)
    if n == 0:
        # Every pattern was pure-epsilon (e.g. "" or "()"): match-all
        # with a single dead state so array shapes stay non-degenerate.
        n = 1
        b.symbols.append(frozenset())
        b.follow.append(set())

    # --- byte-class compression -------------------------------------
    byte_sets = [s for s in b.symbols if isinstance(s, frozenset)]
    sig = np.zeros((256, len(byte_sets)), dtype=bool)
    for j, s in enumerate(byte_sets):
        for byte in s:
            sig[byte, j] = True
    _, byte_class = np.unique(sig, axis=0, return_inverse=True)
    byte_class = byte_class.astype(np.int32)
    n_byte_classes = int(byte_class.max()) + 1 if len(byte_sets) else 1
    begin_class = n_byte_classes
    end_class = n_byte_classes + 1
    pad_class = n_byte_classes + 2
    n_classes = n_byte_classes + 3

    char_mask = np.zeros((n_classes, n), dtype=bool)
    # One representative byte per class is enough: membership is
    # constant within a class by construction.
    rep_byte = np.zeros(n_byte_classes, dtype=np.int32)
    rep_byte[byte_class] = np.arange(256, dtype=np.int32)
    for s_idx, sym in enumerate(b.symbols):
        if sym == BEGIN:
            char_mask[begin_class, s_idx] = True
        elif sym == END:
            char_mask[end_class, s_idx] = True
        else:
            for c in range(n_byte_classes):
                if int(rep_byte[c]) in sym:
                    char_mask[c, s_idx] = True

    follow = np.zeros((n, n), dtype=bool)
    for i, js in enumerate(b.follow):
        for j in js:
            follow[i, j] = True

    inject_v = np.zeros(n, dtype=bool)
    inject_v[list(inject)] = True
    accept_v = np.zeros(n, dtype=bool)
    accept_v[list(accept)] = True

    return NFAProgram(
        n_states=n,
        n_classes=n_classes,
        byte_class=byte_class,
        begin_class=begin_class,
        end_class=end_class,
        pad_class=pad_class,
        char_mask=char_mask,
        follow=follow,
        inject=inject_v,
        accept=accept_v,
        match_all=match_all,
        patterns=tuple(patterns),
    )


def reference_match(prog: NFAProgram, line: bytes) -> bool:
    """Pure-numpy oracle-shaped simulation of the exact update the
    TPU engine runs — used by property tests to separate 'automaton is
    wrong' from 'engine is wrong'."""
    if prog.match_all:
        return True
    classes = (
        [prog.begin_class]
        + [int(prog.byte_class[c]) for c in line]
        + [prog.end_class]
    )
    v = np.zeros(prog.n_states, dtype=bool)
    follow_u8 = prog.follow.astype(np.uint8)
    for c in classes:
        reach = (v.astype(np.uint8) @ follow_u8) > 0
        v = (reach | prog.inject) & prog.char_mask[c]
        if (v & prog.accept).any():
            return True
    return False
