"""Subset-construction DFA over the Glushkov class alphabet — the
strong-CPU host engine.

The round-4 verdict called the K-sequential-`re` CPU baseline soft: a
competent CPU opponent would run one combined pass, not K scans. This
module IS that opponent, built from the same compiler artifacts the TPU
engine uses: determinize the union Glushkov NFA (step semantics
identical to ops.nfa._scan_classes: v' = (follow(v) | inject) &
char_mask[c], accept latched after every step including the BEGIN/END
sentinel steps) over the compressed class alphabet, then scan bytes
through a flat transition table — one table lookup per byte, early-exit
on accept. klogs_tpu.native exposes the C loop (dfa_scan); the numpy
fallback here is the correctness oracle for it.

Subset construction can blow up exponentially, so ``max_states`` caps
it; callers fall back to a combined-alternation `re` (filters.cpu) when
construction overflows or the pattern set uses syntax outside the
compiler's RE2 subset.

Reference analog: none — the reference matches with Go regexp
(/root/reference/cmd/root.go:366); this is the "do better" CPU bar the
TPU multiple is measured against (BASELINE.md row 3).
"""

from collections import deque
from dataclasses import dataclass
from typing import Callable

import numpy as np

from klogs_tpu.filters.compiler.glushkov import NFAProgram

# The 32-pattern north-star set determinizes to 8,544 states; 16k
# leaves headroom for comparable sets while bounding the table at a
# few MB (cache-resident scan) and construction at a couple of seconds.
DEFAULT_MAX_STATES = 16384


@dataclass
class DFATables:
    """Flat scan tables. ``table`` is [n_dfa, n_classes] uint32 state
    ids; ``accept`` a uint8 flag per DFA state; ``byte_class`` the
    int32[256] byte->class map shared with the NFA engine; ``start``
    the state AFTER consuming the BEGIN sentinel (checked for accept
    before any byte: patterns like "^" accept there)."""

    table: np.ndarray
    accept: np.ndarray
    byte_class: np.ndarray
    n_classes: int
    start: int
    end_class: int
    match_all: bool


def build_dfa(prog: NFAProgram,
              max_states: int = DEFAULT_MAX_STATES) -> "DFATables | None":
    """Determinize ``prog``. Returns None when the subset construction
    exceeds ``max_states`` (caller falls back to `re`)."""
    S = prog.n_states
    C = prog.n_classes
    follow = prog.follow.astype(bool)
    inject = prog.inject.astype(bool)
    char_mask = prog.char_mask.astype(bool)  # [C, S]
    accept = prog.accept.astype(bool)

    ids: dict[bytes, int] = {}
    members: list[np.ndarray] = []
    work: deque[int] = deque()

    def intern_key(key: bytes, vec: np.ndarray) -> "int | None":
        sid = ids.get(key)
        if sid is None:
            if len(members) >= max_states:
                return None
            sid = len(members)
            ids[key] = sid
            members.append(vec)
            work.append(sid)
        return sid

    start_vec = np.zeros(S, dtype=bool)
    intern_key(np.packbits(start_vec).tobytes(), start_vec)
    rows: list[np.ndarray] = []
    # Frontier-batched expansion: one bool matmul computes reachability
    # for a whole batch of pending subset-states, one packbits call
    # produces every candidate key — the per-transition Python cost is
    # a single dict lookup (construction is startup/bench-time, but a
    # 50k-state build at naive per-vector numpy cost would take
    # minutes).
    BATCH = 256
    while work:
        k = min(len(work), BATCH)
        sids = [work.popleft() for _ in range(k)]
        mat = np.stack([members[s] for s in sids])  # [k, S]
        # int32 accumulation: a uint8 matmul wraps mod 256, and a state
        # with an exact multiple of 256 active predecessors would
        # silently vanish from the subset (code-review r5).
        reach = (mat.astype(np.int32) @ follow.astype(np.int32)) > 0
        active = reach | inject[None, :]
        # [k, C, S] candidates; packbits over the state axis gives the
        # dict keys for all k*C transitions at once.
        nxt = active[:, None, :] & char_mask[None, :, :]
        keys = np.packbits(nxt.reshape(k * C, S), axis=1)
        klen = keys.shape[1]
        keys_b = keys.tobytes()
        for i in range(k):
            row = np.empty(C, dtype=np.int64)
            for c in range(C):
                j = i * C + c
                tid = intern_key(keys_b[j * klen:(j + 1) * klen],
                                 nxt[i, c])
                if tid is None:
                    return None
                row[c] = tid
            rows.append(row)

    # u16 ids when they fit: the C scan is latency-bound on the random
    # table walk, so halving the footprint matters more than width.
    dt = np.uint16 if len(members) < (1 << 16) else np.uint32
    table = np.vstack(rows).astype(dt)
    acc = np.fromiter(((m & accept).any() for m in members),
                      dtype=np.uint8, count=len(members))
    start = int(table[0, prog.begin_class])
    return DFATables(
        table=np.ascontiguousarray(table),
        accept=acc,
        byte_class=np.ascontiguousarray(prog.byte_class, dtype=np.int32),
        n_classes=C,
        start=start,
        end_class=prog.end_class,
        match_all=bool(prog.match_all),
    )


def _compiler_fingerprint() -> str:
    """Hash of the compiler sources that determine table SEMANTICS
    (parser, Glushkov construction, this module): a semantics bug-fix
    invalidates every cached table automatically — no manually-bumped
    version constant to forget (code-review r5)."""
    import hashlib
    import os

    h = hashlib.sha256()
    here = os.path.dirname(os.path.abspath(__file__))
    for name in ("parser.py", "glushkov.py", "dfa.py"):
        try:
            with open(os.path.join(here, name), "rb") as f:
                h.update(f.read())
        except OSError:
            try:  # zipapp: no real files — read through the loader
                import importlib.resources

                h.update(importlib.resources.files(__package__)
                         .joinpath(name).read_bytes())
            except Exception:
                from klogs_tpu.version import BUILD_VERSION

                h.update(BUILD_VERSION.encode())
    return h.hexdigest()[:16]


_FINGERPRINT = _compiler_fingerprint()


def _cache_path(patterns: "list[str]", ignore_case: bool,
                max_states: int) -> str:
    import hashlib
    import os

    from klogs_tpu.utils.cache import cache_dir

    key = hashlib.sha256(repr(
        (tuple(patterns), bool(ignore_case), int(max_states),
         _FINGERPRINT)).encode()).hexdigest()[:20]
    return os.path.join(cache_dir(), f"dfa-{key}.npz")


# On-disk table-cache size cap (MiB): a 4k-pattern grouped set writes
# ~100 tables, and long-lived hosts cycling many tenant pattern sets
# would otherwise grow ~/.cache without bound. Exceeding the cap
# evicts least-recently-USED tables (mtime, refreshed on every cache
# hit), so the hot sets of a multi-set host stay resident.
DEFAULT_CACHE_MB = 512


def _cache_cap_bytes() -> int:
    import math

    from klogs_tpu.utils.env import read as env_read

    try:
        mb = float(env_read("KLOGS_DFA_CACHE_MB",
                            str(DEFAULT_CACHE_MB)))
    except ValueError:
        return DEFAULT_CACHE_MB * 1048576
    if not math.isfinite(mb) or mb <= 0:
        # A negative/zero/nan cap would evict EVERY table on every
        # write (warm starts silently recompile the world); treat it
        # as the misconfiguration it is, like _env_positive_float.
        return DEFAULT_CACHE_MB * 1048576
    return int(mb * 1048576)


def _evict_lru(keep: str, cap_bytes: "int | None" = None) -> int:
    """Shrink the DFA table cache below the size cap, oldest-touched
    first; ``keep`` (the just-written table) is never evicted. Returns
    the number of files removed. All failures are silent — the cache
    is an optimization, never a correctness dependency."""
    import os

    from klogs_tpu.utils.cache import cache_dir

    cap = _cache_cap_bytes() if cap_bytes is None else cap_bytes
    removed = 0
    try:
        d = cache_dir()
        entries = []
        total = 0
        for name in os.listdir(d):
            if not (name.startswith("dfa-") and name.endswith(".npz")):
                continue
            p = os.path.join(d, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
            total += st.st_size
        entries.sort()
        for _, size, p in entries:
            if total <= cap:
                break
            if os.path.abspath(p) == os.path.abspath(keep):
                continue
            try:
                os.remove(p)
                total -= size
                removed += 1
            except OSError:
                pass
    except OSError:
        pass
    return removed


def build_dfa_cached(patterns: list[str], ignore_case: bool = False,
                     max_states: int = DEFAULT_MAX_STATES,
                     on_event: "Callable[[str], None] | None" = None
                     ) -> "DFATables | None":
    """build_dfa with an LRU disk cache (~/.cache/klogs-tpu) keyed by
    the pattern set: the 32-pattern north-star set determinizes in
    ~1.6s, which would otherwise be paid at every CLI start — and a
    grouped 4k-pattern set pays it ~100x, so warm starts matter even
    more there. Cache failures (no home, corrupt file, race) silently
    rebuild. A hit refreshes the file's mtime (the LRU clock); writes
    that push the cache past KLOGS_DFA_CACHE_MB evict least-recently-
    used tables. ``on_event`` (observability hook) receives "hit",
    "miss", and one "evict" per removed file."""
    import os

    import numpy as _np

    from klogs_tpu.filters.compiler.glushkov import compile_patterns

    def event(kind: str) -> None:
        if on_event is not None:
            on_event(kind)

    path = _cache_path(patterns, ignore_case, max_states)
    try:
        with _np.load(path) as z:
            t = DFATables(
                table=z["table"], accept=z["accept"],
                byte_class=z["byte_class"], n_classes=int(z["n_classes"]),
                start=int(z["start"]), end_class=int(z["end_class"]),
                match_all=bool(z["match_all"]))
        try:
            os.utime(path)  # LRU touch
        except OSError:
            pass
        event("hit")
        return t
    except Exception:
        pass
    event("miss")
    prog = compile_patterns(patterns, ignore_case=ignore_case)
    t = build_dfa(prog, max_states)
    if t is None:
        return None
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            _np.savez(f, table=t.table, accept=t.accept,
                      byte_class=t.byte_class, n_classes=t.n_classes,
                      start=t.start, end_class=t.end_class,
                      match_all=t.match_all)
        os.replace(tmp, path)
        for _ in range(_evict_lru(keep=path)):
            event("evict")
    except Exception:
        pass
    return t


def scan_python(t: DFATables, lines: list[bytes]) -> list[bool]:
    """Pure-Python reference scan (oracle for the C dfa_scan)."""
    out = []
    tab = t.table
    acc = t.accept
    bc = t.byte_class
    for line in lines:
        body = line.rstrip(b"\n")
        if t.match_all:
            out.append(True)
            continue
        s = t.start
        hit = bool(acc[s])
        if not hit:
            for b in body:
                s = int(tab[s, bc[b]])
                if acc[s]:
                    hit = True
                    break
            else:
                s = int(tab[s, t.end_class])
                hit = bool(acc[s])
        out.append(hit)
    return out
