"""LogFilter interface and shared statistics.

This is the new layer the north star inserts at the reference's write
boundary (between the stream read at cmd/root.go:325 and the buffered
file write at cmd/root.go:366): lines go in, a keep/drop verdict per
line comes out, and only kept lines reach the sink.

Implementations:
- RegexFilter (klogs_tpu.filters.cpu): host-side ``re`` engine, the
  CPU baseline (≙ the Go ``regexp`` path in the north star).
- NFAEngineFilter (klogs_tpu.filters.tpu): bit-parallel batch NFA under
  JAX, with jnp and Pallas execution paths.

A line "matches" when ANY of the K patterns matches anywhere in the
line (re.search semantics, unanchored).
"""

import abc
import threading
import time


class FilterStats:
    """Aggregate pipeline statistics, for the --stats summary and the
    north-star metrics (lines/sec, matched %, batch latency).

    A VIEW over an obs.Registry — every number lives in a registered
    metric family (the same objects a /metrics scrape or --stats-json
    dump reads), so the summary and the instrument panel can never
    disagree. By default each FilterStats owns a private Registry
    (isolated pipelines/tests); the --metrics-port paths pass the
    process-global ``obs.REGISTRY`` so the sidecar scrapes live values.

    Three latency series are kept separate so saturation diagnosis is
    possible (the e2e number conflates them):
    - batch (e2e): sink-observed await, enqueue -> verdicts.
    - queue: enqueue -> device dispatch (coalescing + backpressure wait),
      recorded by AsyncFilterService.
    - device: dispatch -> verdicts fetched, recorded by
      AsyncFilterService.
    """

    def __init__(self, registry=None):
        from klogs_tpu.obs.metrics import Registry

        self.registry = registry if registry is not None else Registry()
        r = self.registry
        self._lines_in = r.family("klogs_sink_lines_total")
        self._lines_matched = r.family("klogs_sink_lines_matched_total")
        self._bytes_in = r.family("klogs_sink_bytes_in_total")
        self._bytes_out = r.family("klogs_sink_bytes_out_total")
        self._batches = r.family("klogs_sink_batches_total")
        self._deadline_flushes = r.family("klogs_sink_deadline_flush_total")
        self._batch = r.family("klogs_sink_batch_latency_seconds")
        self._queue = r.family("klogs_coalescer_queue_wait_seconds")
        self._device = r.family("klogs_engine_device_batch_seconds")
        # Two-phase (prefilter) visibility: without these a user cannot
        # tell whether gating is engaged, let alone winning.
        self._pf_lines = r.family("klogs_engine_prefilter_lines_total")
        self._pf_candidates = r.family(
            "klogs_engine_prefilter_candidates_total")
        self._pf_tiles = r.family("klogs_engine_prefilter_tiles_total")
        self._pf_tiles_live = r.family(
            "klogs_engine_prefilter_tiles_live_total")
        self._compiles = r.family("klogs_engine_compile_total")
        self._bucket_width = r.family("klogs_engine_bucket_width_bytes")
        self._pad_bytes = r.family("klogs_engine_pad_bytes_total")
        self._payload_bytes = r.family("klogs_engine_payload_bytes_total")
        # Device-sweep visibility (thousand-pattern fused path): which
        # narrowing stage ran, what it let through, and the degrade /
        # bypass events an operator needs to explain a throughput step.
        self._sweep_batches = r.family("klogs_sweep_batches_total")
        self._sweep_lines = r.family("klogs_sweep_lines_total")
        self._sweep_cand = r.family("klogs_sweep_candidate_lines_total")
        self._sweep_fallback = r.family("klogs_sweep_fallback_total")
        # Degrade-policy visibility (--on-filter-error, resilience):
        # batches/lines that bypassed or skipped filtering because the
        # filter service was unavailable.
        self._degraded_batches = r.family(
            "klogs_filter_degraded_batches_total")
        self._degraded_lines = r.family(
            "klogs_filter_degraded_lines_total")
        self.pf_disabled_reason: str | None = None
        self.started_at = time.perf_counter()
        # Warmup boundary: timestamp when the FIRST batch started
        # filtering. lines_per_sec measures from here, not from pipeline
        # construction — otherwise jit warmup deflates short runs
        # (VERDICT r1). Written by the dispatch loop AND by synchronous
        # record_batch fallbacks that benches drive from plain threads,
        # so the first-write race is settled under a lock (declared in
        # the lock-discipline table, tools/analysis).
        self._t_lock = threading.Lock()
        self.first_batch_started_at: float | None = None

    # -- counter views (the pre-registry attribute API) ---------------
    @property
    def lines_in(self) -> int:
        return int(self._lines_in.value)

    @property
    def lines_matched(self) -> int:
        return int(self._lines_matched.value)

    @property
    def degraded_lines(self) -> int:
        """Lines that took ANY degrade action (pass/drop), summed
        across actions — the --backfill "shed" accounting."""
        return int(sum(child.value
                       for _lv, child in self._degraded_lines.children()))

    @property
    def bytes_in(self) -> int:
        return int(self._bytes_in.value)

    @property
    def bytes_out(self) -> int:
        return int(self._bytes_out.value)

    @property
    def batches(self) -> int:
        return int(self._batches.value)

    @property
    def pf_lines(self) -> int:
        return int(self._pf_lines.value)

    @property
    def pf_candidates(self) -> int:
        return int(self._pf_candidates.value)

    @property
    def pf_tiles_total(self) -> int:
        return int(self._pf_tiles.value)

    @property
    def pf_tiles_live(self) -> int:
        return int(self._pf_tiles_live.value)

    def mark_batch_started(self, t: float | None = None) -> None:
        """Record the true start of the first filtered batch. Called at
        DISPATCH time (AsyncFilterService), so lines/sec on short runs
        is not overstated by back-computing the start from the first
        completion (which credits the whole first-batch latency as
        warmup)."""
        with self._t_lock:
            if self.first_batch_started_at is None:
                self.first_batch_started_at = (
                    t if t is not None else time.perf_counter())

    def record_batch(self, n_lines: int, n_matched: int, n_bytes_in: int,
                     n_bytes_out: int, latency_s: float) -> None:
        with self._t_lock:
            if self.first_batch_started_at is None:
                # Fallback for synchronous paths that never mark dispatch.
                self.first_batch_started_at = (
                    time.perf_counter() - latency_s)
        self._lines_in.inc(n_lines)
        self._lines_matched.inc(n_matched)
        self._bytes_in.inc(n_bytes_in)
        self._bytes_out.inc(n_bytes_out)
        self._batches.inc()
        # Exemplar: when a trace is recording this batch, the latency
        # sample links to it in the exposition — a p99 outlier points
        # straight at its hop-by-hop story.
        from klogs_tpu.obs.trace import TRACER

        self._batch.observe(latency_s, exemplar=TRACER.exemplar())

    def record_prefilter(self, n_lines: int, n_candidates: int,
                         n_tiles: int, n_tiles_live: int) -> None:
        self._pf_lines.inc(n_lines)
        self._pf_candidates.inc(n_candidates)
        self._pf_tiles.inc(n_tiles)
        self._pf_tiles_live.inc(n_tiles_live)

    def record_sweep(self, path: str, n_lines: int,
                     n_candidates: int) -> None:
        """One batch narrowed by the literal sweep: ``path`` is which
        stage ran (device = fused on-device sweep, host = host factor
        sweep)."""
        self._sweep_batches.labels(path=path).inc()
        self._sweep_lines.labels(path=path).inc(n_lines)
        self._sweep_cand.labels(path=path).inc(n_candidates)

    def record_sweep_fallback(self) -> None:
        """The device sweep degraded (build or kernel failure) and the
        batch ran on the fallback path instead."""
        self._sweep_fallback.inc()
        from klogs_tpu.obs.trace import flight_trigger

        flight_trigger("sweep-fallback")

    def record_queue_wait(self, wait_s: float) -> None:
        self._queue.observe(wait_s)

    def record_device_batch(self, latency_s: float) -> None:
        from klogs_tpu.obs.trace import TRACER

        self._device.observe(latency_s, exemplar=TRACER.exemplar())

    def record_deadline_flush(self) -> None:
        """A flush forced by the follow-mode deadline (not batch size)
        — the signal that sinks are running latency-bound."""
        self._deadline_flushes.inc()

    def record_degraded(self, action: str, n_lines: int) -> None:
        """One sink flush handled by the --on-filter-error degrade
        policy instead of the filter (service unavailable): ``action``
        is what happened to its lines (pass = written unfiltered,
        drop = discarded)."""
        self._degraded_batches.labels(action=action).inc()
        self._degraded_lines.labels(action=action).inc(n_lines)

    def record_engine_batch(self, width: int, rows: int,
                            payload_bytes: int) -> None:
        """One width-bucketed sub-batch dispatched to the device:
        tracks the bucket-width distribution and padding waste
        (bucketed tensor area minus useful payload)."""
        self._bucket_width.observe(width)
        self._payload_bytes.inc(payload_bytes)
        self._pad_bytes.inc(max(0, width * rows - payload_bytes))

    def record_compile(self) -> None:
        """A (width, rows) batch geometry first seen by the engine —
        one jit trace/compile (the cold-start cost /readyz guards)."""
        self._compiles.inc()

    def percentile_latency_s(self, q: float) -> float:
        return self._batch.percentile(q)

    def percentile_queue_s(self, q: float) -> float:
        return self._queue.percentile(q)

    def percentile_device_s(self, q: float) -> float:
        return self._device.percentile(q)

    @property
    def has_service_latencies(self) -> bool:
        return self._device.count > 0

    def lines_per_sec(self) -> float:
        start = (self.first_batch_started_at
                 if self.first_batch_started_at is not None
                 else self.started_at)
        elapsed = time.perf_counter() - start
        return self.lines_in / elapsed if elapsed > 0 else 0.0

    def matched_pct(self) -> float:
        return 100.0 * self.lines_matched / self.lines_in if self.lines_in else 0.0


# Offsets ride int32 (device-friendly, half the index bandwidth of
# int64); batches past this must be split upstream, never silently
# wrapped into negative offsets.
_INT32_MAX = 2**31 - 1


def frame_lines(lines: list[bytes], strip_nl: bool = True):
    """list[bytes] -> (payload, offsets: int32[n+1], raw_total) — the
    framed-batch builder (one contiguous buffer + prefix sums instead of
    n PyBytes). Trailing-newline runs are stripped when strip_nl, the
    engine's rstrip(b"\\n") parity rule; raw_total is the UNstripped
    byte count (stats bytes-in). Native single-pass when built."""
    import numpy as np

    from klogs_tpu.native import hostops

    if hostops is not None and hasattr(hostops, "frame_lines"):
        payload, offs, raw = hostops.frame_lines(lines, int(strip_nl))
        return payload, np.frombuffer(offs, dtype=np.int32), raw
    raw = sum(len(ln) for ln in lines)
    bodies = [ln.rstrip(b"\n") for ln in lines] if strip_nl else lines
    # Stripping only shrinks, so raw bounds the payload: the second
    # (stripped) sum runs only for batches that could actually wrap.
    if raw > _INT32_MAX and sum(len(b) for b in bodies) > _INT32_MAX:
        # Parity with the native packer: int32 cumsum would silently
        # wrap into negative offsets (empty mis-sliced lines downstream)
        # — fail loudly instead.
        raise OverflowError(
            f"framed batch payload (> {_INT32_MAX} bytes) exceeds "
            "int32 offsets; split the batch")
    offsets = np.zeros(len(lines) + 1, dtype=np.int32)
    if bodies:
        offsets[1:] = np.cumsum(
            np.fromiter((len(b) for b in bodies), np.int32, len(bodies)))
    return b"".join(bodies), offsets, raw


def pack_framed_rows(payload: bytes, offsets, width: int,
                     rows: "int | None" = None, sel=None, lens=None):
    """Framed batch -> ([rows, width] u8 zero-padded row batch,
    [B] int64 lens): the vectorized ragged scatter that turns the
    collector's contiguous payload into the packed row layout device
    kernels consume (the inverse of frame_lines, minus the padding).
    Every payload byte's destination is its row stride minus the
    source line start — one fancy-indexed assignment, no per-line
    PyBytes. ``rows`` >= B pads extra zero rows (jit-cache row
    bucketing); rows beyond B and columns beyond each line stay zero.
    Callers must ensure every line fits ``width``.

    ``sel`` (int row indices) packs only those frame rows, in ``sel``
    order; ``lens`` overrides the per-row byte counts (selected rows
    when ``sel`` is given) — how the TPU engine's framed byte entry
    packs one width bucket with trailing newlines stripped. Shared by
    that entry, the IndexedFilter device-sweep path, and bench.py so
    the bench times the SAME packer production runs."""
    import numpy as np

    offsets = np.asarray(offsets)
    starts = offsets[:-1].astype(np.int64)
    contiguous = sel is None and lens is None
    if sel is not None:
        starts = starts[sel]
        if lens is None:
            lens = np.diff(offsets).astype(np.int64)[sel]
    if lens is None:
        lens = np.diff(offsets).astype(np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    B = len(lens)
    if rows is None:
        rows = B
    batch = np.zeros((rows, width), dtype=np.uint8)
    total = int(lens.sum())
    if total:
        arr = np.frombuffer(payload, dtype=np.uint8)
        row_base = np.arange(B, dtype=np.int64) * width
        if contiguous:
            # Whole frame, unmodified lens: the source indices are one
            # arange over the payload span.
            shift = np.repeat(row_base - starts, lens)
            src = np.arange(int(offsets[0]), int(offsets[-1]),
                            dtype=np.int64)
            batch.reshape(-1)[src + shift] = arr[src]
        else:
            # General ragged gather/scatter (row subset and/or
            # stripped lens): absolute source index per byte via the
            # standard ragged-range trick.
            ends = np.cumsum(lens)
            intra = np.arange(total, dtype=np.int64) - np.repeat(
                ends - lens, lens)
            src = np.repeat(starts, lens) + intra
            batch.reshape(-1)[np.repeat(row_base, lens) + intra] = arr[src]
    return batch, lens


def split_frame(payload: bytes, offsets) -> list[bytes]:
    """Framed batch -> list[bytes] (line i = payload[offsets[i]:
    offsets[i+1]]) — the bridge for engines without a framed fast path.
    ``offsets`` is an int32 numpy array of n+1 exclusive prefix sums."""
    from klogs_tpu.native import hostops

    n = len(offsets) - 1
    if hostops is not None and hasattr(hostops, "split_frame"):
        import numpy as np

        return hostops.split_frame(
            payload, np.ascontiguousarray(offsets, dtype=np.int32), n)
    if not isinstance(payload, bytes):
        payload = bytes(payload)  # memoryview slab: slices must be bytes
    return [payload[offsets[i]:offsets[i + 1]] for i in range(n)]


class LogFilter(abc.ABC):
    """K-pattern any-match line filter."""

    @abc.abstractmethod
    def match_lines(self, lines: list[bytes]) -> list[bool]:
        """One verdict per line; True = keep. Lines include no trailing
        newline requirement — implementations must tolerate either."""

    # -- two-phase API for pipelined execution ------------------------
    # Device engines override these so a batch can be ENQUEUED without
    # blocking on its result: dispatch() returns an opaque handle after
    # (cheap, async) submission; fetch() blocks until the verdicts are
    # ready. The default degrades to synchronous matching, so every
    # filter is usable behind AsyncFilterService.

    def dispatch(self, lines: list[bytes]):
        return self.match_lines(lines)

    def fetch(self, handle) -> list[bool]:
        return handle

    # -- framed API ---------------------------------------------------
    # A "framed batch" is (payload: bytes, offsets: int32[n+1] prefix
    # sums): one contiguous buffer instead of n PyBytes. It is the
    # zero-per-line-object representation the service/wire path rides
    # (per-line msgpack objects measured ~1us/line of pure overhead on
    # the single-core loopback — SERVICE_BENCH.json round-4 rows).
    # Engines with a native framed packer override dispatch_framed;
    # the default bridges through the list path so every filter works.
    # fetch_framed returns a numpy bool array (callers count/slice it
    # without materializing per-line Python bools).

    def dispatch_framed(self, payload: bytes, offsets):
        return self.dispatch(split_frame(payload, offsets))

    def fetch_framed(self, handle):
        import numpy as np

        return np.asarray(self.fetch(handle), dtype=bool)

    def close(self) -> None:
        """Release engine resources (device buffers, transports)."""


class IncludeExcludeFilter(LogFilter):
    """keep = (no include set OR include matches) AND NOT exclude
    matches — the stern-style noise-suppression combinator. Both sides
    are independent LogFilters; dispatch() submits BOTH batches before
    either result is awaited, so on device engines the two automata
    pipeline instead of serializing round trips."""

    def __init__(self, include: "LogFilter | None", exclude: LogFilter):
        self.include = include
        self.exclude = exclude

    def match_lines(self, lines: list[bytes]) -> list[bool]:
        return self.fetch(self.dispatch(lines))

    def dispatch(self, lines: list[bytes]):
        hi = self.include.dispatch(lines) if self.include is not None else None
        he = self.exclude.dispatch(lines)
        return (hi, he)

    def fetch(self, handle) -> list[bool]:
        hi, he = handle
        ex = self.exclude.fetch(he)
        if hi is None:
            return [not e for e in ex]
        inc = self.include.fetch(hi)
        return [i and not e for i, e in zip(inc, ex)]

    def dispatch_framed(self, payload: bytes, offsets):
        # When NEITHER side has a native framed path, split once and
        # share the list — the per-side default bridge would run
        # split_frame twice over the same payload (2n allocations on
        # the flush hot path).
        def bridged(f):
            return (f is None
                    or type(f).dispatch_framed is LogFilter.dispatch_framed)

        if bridged(self.include) and bridged(self.exclude):
            return ("list", self.dispatch(split_frame(payload, offsets)))
        hi = (self.include.dispatch_framed(payload, offsets)
              if self.include is not None else None)
        he = self.exclude.dispatch_framed(payload, offsets)
        return ("framed", (hi, he))

    def fetch_framed(self, handle):
        import numpy as np

        kind, inner = handle
        if kind == "list":
            return np.asarray(self.fetch(inner), dtype=bool)
        hi, he = inner
        ex = self.exclude.fetch_framed(he)
        if hi is None:
            return ~ex
        return self.include.fetch_framed(hi) & ~ex

    def close(self) -> None:
        if self.include is not None:
            self.include.close()
        self.exclude.close()


def build_include_exclude(builder, patterns: list[str],
                          exclude: "list[str] | None") -> LogFilter:
    """Compose include/exclude pattern sets over a single-engine
    ``builder(pats) -> LogFilter`` — THE one place the combination
    logic lives (collector and filterd both call it, so they can never
    drift). Raises when both sets are empty: a pipeline with no
    patterns at all has nothing to decide."""
    exclude = exclude or []
    if not patterns and not exclude:
        raise ValueError("need at least one include or exclude pattern")
    include = builder(patterns) if patterns else None
    if exclude:
        return IncludeExcludeFilter(include, builder(exclude))
    return include
