"""FilteredSink: the write-gating stage.

Sits exactly where the reference writes bytes to disk
(writeLogToDisk, cmd/root.go:359-374), but frames chunks into lines,
asks a LogFilter for a keep-mask, and writes only kept lines — in
the original per-file order (matching is batched, writes are ordered).

Batching policy: lines accumulate until ``batch_lines`` is reached, then
one filter call covers them (amortizing engine overhead — essential for
the TPU path). ``deadline_s`` bounds how long a pending line can wait in
follow mode; the deadline is enforced on the next write and by the
runner's periodic flush.
"""

import asyncio
import time
from dataclasses import dataclass
from dataclasses import field as dataclasses_field
from typing import Callable

from klogs_tpu.filters.base import FilterStats, LogFilter
from klogs_tpu.filters.framer import LineFramer
from klogs_tpu.obs import trace
from klogs_tpu.resilience import Unavailable
from klogs_tpu.runtime.fanout import StreamJob
from klogs_tpu.runtime.sink import FileSink, Sink
from klogs_tpu.ui import term


class FilteredSink(Sink):
    def __init__(
        self,
        inner: Sink,
        log_filter: LogFilter,
        stats: FilterStats,
        batch_lines: int = 1024,
        deadline_s: float = 0.05,
        on_close: "Callable[[FilteredSink], None] | None" = None,
        service: "AsyncFilterService | None" = None,
        on_filter_error: str = "abort",
    ):
        self._inner = inner
        self._filter = log_filter
        self._stats = stats
        self._pending_since: float | None = None
        self._batch_lines = batch_lines
        self._deadline_s = deadline_s
        self._on_close = on_close
        self._closed = False
        self._service = service
        # Degrade routing when the filter service is Unavailable
        # (retries exhausted / breaker open): "pass" writes the batch
        # unfiltered, "drop" discards it, "abort" (default) propagates
        # — one friendly fatal line, reference-style.
        self._on_filter_error = on_filter_error
        self._degrade_warned = False
        # Fully-framed hot path when the native module and a framed
        # service are both present: chunks accumulate in ONE contiguous
        # buffer (C newline sweep), the verdicts come back as a numpy
        # mask, and kept lines are span-gathered from the same buffer —
        # no per-line Python object anywhere between the HTTP read and
        # the file write. Otherwise the list path (LineFramer +
        # list[bytes]) keeps identical semantics.
        self._batcher = None
        if (service is not None and hasattr(service, "match_framed")) or (
                service is None and log_filter is not None):
            from klogs_tpu.filters.framer import FramedBatcher

            try:
                self._batcher = FramedBatcher()
            except RuntimeError:
                pass
        self._framer = LineFramer() if self._batcher is None else None
        self._pending: list[bytes] = []
        # Held across match+write so concurrent flushes (write vs the
        # deadline flusher) cannot reorder this file's lines while a
        # batch is in flight on the async service. Created lazily on
        # first flush: on Py3.10 an asyncio primitive binds the loop
        # that exists at CONSTRUCTION, and sinks are built by
        # make_pipeline before asyncio.run() starts the real one.
        self._flush_lock: "asyncio.Lock | None" = None

    def _pending_count(self) -> int:
        if self._batcher is not None:
            return self._batcher.pending_lines
        return len(self._pending)

    async def write(self, chunk: bytes) -> None:
        if self._batcher is not None:
            had = self._batcher.pending_lines
            n = self._batcher.feed(chunk)
            if n and not had:
                self._pending_since = time.perf_counter()
        else:
            lines = self._framer.feed(chunk)
            if lines:
                if not self._pending:
                    self._pending_since = time.perf_counter()
                self._pending.extend(lines)
            n = len(self._pending)
        if n >= self._batch_lines or (
            n
            and self._pending_since is not None
            and time.perf_counter() - self._pending_since >= self._deadline_s
        ):
            await self._flush_pending()

    async def _flush_pending(self, final: bool = False) -> None:
        # One span per flush: the batch's first hop when no fanout span
        # is active (deadline flusher, close), otherwise a child of the
        # chunk's fanout.read span — either way the root of everything
        # downstream (coalescer/shard/RPC/device/write).
        if self._flush_lock is None:
            self._flush_lock = asyncio.Lock()
        with trace.TRACER.span("sink.flush",
                               pending=self._pending_count()):
            async with self._flush_lock:
                await self._flush_pending_locked(final=final)

    async def _flush_pending_locked(self, final: bool = False) -> None:
        if self._batcher is not None:
            await self._flush_framed(final)
            return
        pending, self._pending = self._pending, []
        self._pending_since = None
        if not pending:
            return
        t0 = time.perf_counter()
        from klogs_tpu.native import hostops

        if self._service is not None and hasattr(self._service,
                                                 "match_framed"):
            # Framed flush over list pending (native module absent or
            # arrived late): one pass builds (payload, offsets), the
            # verdicts come back as a numpy array.
            import numpy as np

            from klogs_tpu.filters.base import frame_lines

            payload, offsets, bytes_in = frame_lines(pending)
            try:
                mask_arr = await self._service.match_framed(payload, offsets)
            except Unavailable as e:
                await self._degrade(e, n_lines=len(pending), payload=payload)
                return
            self._note_recovered()
            latency = time.perf_counter() - t0
            n_kept = int(np.count_nonzero(mask_arr))
            mask_b = np.ascontiguousarray(mask_arr, dtype=np.uint8).tobytes()
            if hostops is not None:
                out = hostops.join_kept(pending, mask_b)
            else:
                out = b"".join(
                    ln for ln, keep in zip(pending, mask_b) if keep)
        else:
            if self._service is not None:
                try:
                    mask = await self._service.match(pending)
                except Unavailable as e:
                    await self._degrade(e, n_lines=len(pending),
                                        payload=b"".join(pending))
                    return
                self._note_recovered()
            else:
                mask = self._filter.match_lines(pending)
            latency = time.perf_counter() - t0
            n_kept = sum(mask)
            if hostops is not None:
                out = hostops.join_kept(pending, bytes(bytearray(mask)))
            else:
                out = b"".join(ln for ln, keep in zip(pending, mask) if keep)
            bytes_in = sum(len(ln) for ln in pending)
        if out:
            with trace.TRACER.span("sink.write", bytes=len(out)):
                await self._inner.write(out)
        self._stats.record_batch(
            n_lines=len(pending),
            n_matched=n_kept,
            n_bytes_in=bytes_in,
            n_bytes_out=len(out),
            latency_s=latency,
        )

    async def _flush_framed(self, final: bool) -> None:
        """The zero-per-line flush: framed batch in, span-gathered
        kept bytes out."""
        import numpy as np

        payload, offsets, n = self._batcher.take(final=final)
        self._pending_since = None
        if n == 0:
            return
        t0 = time.perf_counter()
        if self._service is not None:
            try:
                mask_arr = await self._service.match_framed(payload, offsets)
            except Unavailable as e:
                await self._degrade(e, n_lines=n, payload=payload)
                return
            self._note_recovered()
        else:
            # Direct sync engine (--backend=cpu): the DFA scan releases
            # the GIL and runs at millions of lines/s — no service hop.
            mask_arr = self._filter.fetch_framed(
                self._filter.dispatch_framed(payload, offsets))
        latency = time.perf_counter() - t0
        n_kept = int(np.count_nonzero(mask_arr))
        out = self._batcher._hostops.join_kept_framed(
            payload, np.ascontiguousarray(offsets), n,
            np.ascontiguousarray(mask_arr, dtype=np.uint8).tobytes())
        if out:
            with trace.TRACER.span("sink.write", bytes=len(out)):
                await self._inner.write(out)
        self._stats.record_batch(
            n_lines=n,
            n_matched=n_kept,
            n_bytes_in=len(payload),
            n_bytes_out=len(out),
            latency_s=latency,
        )

    async def _degrade(self, e: Unavailable, *, n_lines: int,
                       payload: bytes) -> None:
        """Route a batch whose filter service is Unavailable per
        --on-filter-error: pass = write unfiltered, drop = discard,
        abort = propagate (the run ends with one friendly line). The
        choice is counted per action so a scrape shows exactly how many
        lines rode each degrade path. Against a sharded --remote fleet
        the service only raises Unavailable after every endpoint has
        failed (partial-fleet failure is rerouted upstream, never
        degraded), so this path still means 'filtering is truly
        gone'."""
        # Flight recorder: a degraded batch is exactly the event an
        # operator reconstructs after the fact — arm a dump carrying
        # this batch's hop story (trace event rides the sink.flush
        # span; the trigger writes when the trace completes).
        trace.TRACER.event("sink.degrade",
                           action=self._on_filter_error, error=str(e))
        trace.flight_trigger("filter-degrade",
                             action=self._on_filter_error, error=str(e))
        if self._on_filter_error == "abort":
            raise e
        if not self._degrade_warned:
            self._degrade_warned = True
            term.warning(
                "filter service unavailable (%s); --on-filter-error=%s: "
                "%s lines until it recovers", e, self._on_filter_error,
                "writing UNFILTERED" if self._on_filter_error == "pass"
                else "DROPPING")
        if self._on_filter_error == "pass" and payload:
            await self._inner.write(payload)
        self._stats.record_degraded(self._on_filter_error, n_lines)

    def _note_recovered(self) -> None:
        # One line when filtering resumes after a degraded stretch —
        # the operator bookend to the degrade warning.
        if self._degrade_warned:
            self._degrade_warned = False
            term.info("filter service recovered; filtering resumed")

    async def flush_if_stale(self) -> None:
        """Flush pending lines whose deadline has passed (called by the
        pipeline's periodic follow-mode flusher)."""
        if (
            self._pending_count()
            and self._pending_since is not None
            and time.perf_counter() - self._pending_since >= self._deadline_s
        ):
            # Deadline-forced (not size-triggered) flushes are the
            # latency-bound signal operators size batch_lines by.
            self._stats.record_deadline_flush()
            await self._flush_pending()
            # Live tailing: matched lines must reach the file, not sit in
            # the inner sink's write buffer.
            await self._inner.flush()

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._on_close is not None:
            self._on_close(self)
        try:
            if self._batcher is None:
                rest = self._framer.flush()
                if rest is not None:
                    self._pending.append(rest)
            await self._flush_pending(final=True)
        finally:
            # The inner sink (file fd) is released even when the final
            # flush dies on an unavailable service or a full disk.
            await self._inner.close()

    @property
    def bytes_written(self) -> int:
        return self._inner.bytes_written


@dataclass
class FilterPipeline:
    """Shared engine + stats across all per-container sinks.

    ``log_filter`` may be None when ``service`` is a remote client (the
    engine lives in the filterd process); sinks then always go through
    the service."""

    log_filter: LogFilter | None
    stats: FilterStats
    batch_lines: int = 1024
    deadline_s: float = 0.05
    service: "AsyncFilterService | None" = None
    patterns: list[str] | None = None
    ignore_case: bool = False
    exclude: list[str] | None = None
    # --on-filter-error degrade routing for every sink this pipeline
    # builds (pass|drop|abort; see FilteredSink).
    on_filter_error: str = "abort"
    # Where gated lines land; None = the reference behavior (a FileSink
    # on job.path). ``-o stdout|both`` injects console/tee factories.
    inner_factory: "Callable[[StreamJob], Sink] | None" = None
    _live_sinks: "set[FilteredSink]" = dataclasses_field(default_factory=set)

    def sink_factory(self, job: StreamJob) -> Sink:
        inner = (self.inner_factory(job) if self.inner_factory is not None
                 else FileSink(job.path))
        sink = FilteredSink(
            inner,
            self.log_filter,
            self.stats,
            batch_lines=self.batch_lines,
            deadline_s=self.deadline_s,
            on_close=self._live_sinks.discard,
            service=self.service,
            on_filter_error=self.on_filter_error,
        )
        self._live_sinks.add(sink)
        return sink

    async def run_deadline_flusher(self,
                                   stop: "asyncio.Event | None" = None
                                   ) -> None:
        """Follow-mode latency bound: periodically force pending lines in
        every live sink through the filter, so a matching line from a
        quiet container appears within ~deadline_s even if no further
        chunks arrive. Run as a background task; cancel to stop.

        ``--on-filter-error=abort`` escalation: an Unavailable raised by
        a stale flush means the documented "end the run with one clear
        error" — set ``stop`` (graceful stream teardown) and re-raise so
        the awaiter surfaces it, instead of quietly dropping the batch
        of an idle stream that will never write again."""
        while True:
            await asyncio.sleep(self.deadline_s / 2)
            # Concurrent: a serial sweep over N slow flushes would make
            # the sweep period N x the flush latency (observed: minutes
            # at 200 sinks). With the coalescing service these merge
            # into a handful of device batches anyway. Per-sink fault
            # isolation: one dead SINK (SinkError) must not kill the
            # flusher for every healthy stream — its own worker
            # surfaces that failure at the next write.
            results = await asyncio.gather(
                *[s.flush_if_stale() for s in list(self._live_sinks)],
                return_exceptions=True,
            )
            for r in results:
                if isinstance(r, Unavailable):
                    term.error("filter service unavailable and "
                               "--on-filter-error=abort: stopping (%s)", r)
                    # The run is ending on a degrade: flush the armed
                    # dump NOW — no further root span may ever finish.
                    trace.flight_trigger("abort-escalation", error=str(r))
                    trace.RECORDER.flush()
                    if stop is not None:
                        stop.set()
                    raise r
                if isinstance(r, Exception):
                    term.warning("deadline flush failed: %s", r)

    async def start(self) -> None:
        """Pre-flight: remote services verify the collector's pattern
        set against the server's before any line flows."""
        verify = getattr(self.service, "verify_patterns", None)
        if verify is not None and self.patterns is not None:
            await verify(self.patterns, self.ignore_case,
                         exclude=self.exclude or [])

    async def aclose(self) -> None:
        """Awaited teardown (run_async calls this): services that hold
        loop resources (grpc channel, in-flight batch tasks) shut down
        cleanly inside the loop instead of leaking fire-and-forget
        tasks into interpreter exit."""
        aclose = getattr(self.service, "aclose", None)
        if aclose is not None:
            await aclose()
        elif self.service is not None:
            self.service.close()
        elif self.log_filter is not None:
            self.log_filter.close()

    def close(self) -> None:
        if self.service is not None:
            self.service.close()  # in-process: also closes the filter
        elif self.log_filter is not None:
            self.log_filter.close()

    def print_summary(self) -> None:
        s = self.stats
        term.info(
            "Filter stats: %d lines in, %d matched (%.1f%%), %.0f lines/sec, "
            "batch latency p50=%.2fms p99=%.2fms (%d batches)",
            s.lines_in, s.lines_matched, s.matched_pct(), s.lines_per_sec(),
            s.percentile_latency_s(50) * 1e3, s.percentile_latency_s(99) * 1e3,
            s.batches,
        )
        if s.has_service_latencies:
            # Split so saturation is diagnosable: queue = coalesce +
            # backpressure wait before dispatch; device = engine time.
            term.info(
                "  queue p50=%.2fms p99=%.2fms | device p50=%.2fms p99=%.2fms",
                s.percentile_queue_s(50) * 1e3, s.percentile_queue_s(99) * 1e3,
                s.percentile_device_s(50) * 1e3,
                s.percentile_device_s(99) * 1e3,
            )
        if s.pf_lines:
            term.info(
                "  prefilter: %.1f%% candidates (%d/%d lines), "
                "%d/%d tiles skipped",
                100.0 * s.pf_candidates / s.pf_lines,
                s.pf_candidates, s.pf_lines,
                s.pf_tiles_total - s.pf_tiles_live, s.pf_tiles_total,
            )
        elif s.pf_disabled_reason:
            term.info("  %s", s.pf_disabled_reason)


def _build_filter(patterns: list[str], backend: str, stats,
                  ignore_case: bool) -> "LogFilter":
    """One engine for one pattern set (shared by the include and
    exclude sides so both always get the same backend treatment)."""
    if backend == "cpu":
        # Strongest host engine the set admits (native DFA scan ->
        # combined-re -> K-sequential re); KLOGS_CPU_ENGINE overrides.
        from klogs_tpu.filters.cpu import best_host_filter

        return best_host_filter(
            patterns, ignore_case=ignore_case,
            registry=stats.registry if stats is not None else None)[0]
    import jax

    from klogs_tpu.filters.tpu import NFAEngineFilter

    # Multi-chip: shard lines (data) x pattern groups over the mesh;
    # single chip: plain on-device batches, no collective overhead.
    engine = None
    if jax.device_count() > 1:
        from klogs_tpu.parallel.mesh import MeshEngine

        # Real chips: per-shard Pallas kernel; virtual/CPU meshes:
        # GSPMD over the jnp path (kernel needs Mosaic or interpret).
        impl = "pallas" if jax.default_backend() != "cpu" else "gspmd"
        engine = MeshEngine(patterns, ignore_case=ignore_case, impl=impl)
    return NFAEngineFilter(patterns, ignore_case=ignore_case,
                           engine=engine, stats=stats)


def _env_positive_float(name: str, default: float) -> float:
    """Env-tunable positive float; zero/negative/nan/inf/garbage is
    rejected as ServiceConfigError naming the variable (a bad knob must
    not surface as a mystery timeout/latency downstream). The
    validation itself is the shared one in klogs_tpu.utils.env."""
    from klogs_tpu.service.client import ServiceConfigError
    from klogs_tpu.utils.env import positive_float

    return positive_float(name, default, exc=ServiceConfigError)


def make_pipeline(patterns: list[str], backend: str,
                  batch_lines: int | None = None,
                  deadline_s: float = 0.05,
                  remote: str | None = None,
                  ignore_case: bool = False,
                  exclude: list[str] | None = None,
                  registry=None,
                  on_filter_error: str = "abort",
                  shard_mode: str = "round-robin",
                  resolver: str | None = None,
                  kubeconfig: str | None = None) -> FilterPipeline:
    # ``registry`` (an obs.Registry) shares the stats backing store
    # with a /metrics sidecar or --stats-json dump; None keeps the
    # pipeline's numbers private (default, and what tests rely on).
    stats = FilterStats(registry=registry)
    service = None
    exclude = exclude or []
    if remote is not None or resolver is not None:
        from klogs_tpu.service.client import RemoteFilterClient
        from klogs_tpu.service.shard import (
            DEFAULT_HEDGE_S,
            DEFAULT_PROBE_INTERVAL_S,
            ShardedFilterClient,
            parse_endpoints,
            pattern_fingerprint,
        )

        # Transport security for the cross-node collector->filterd hop,
        # via env (a --remote deployment is configured by manifest, not
        # interactive flags): KLOGS_REMOTE_TLS_CA switches to TLS,
        # _TLS_CERT/_TLS_KEY add mTLS, _TOKEN_FILE attaches bearer auth
        # (passed as a path: the client re-reads it per RPC, so a
        # rotated mounted Secret keeps working mid-follow). A bad combo
        # raises ServiceConfigError, which the CLI maps to one friendly
        # line — no SystemExit from library code.
        # Per-RPC deadline: KLOGS_REMOTE_TIMEOUT_S bounds each attempt
        # (retry/backoff/breaker defaults live in the client; see
        # docs/RESILIENCE.md). Zero/negative would DEADLINE_EXCEED
        # every attempt with an error that never names the env var.
        rpc_timeout_s = _env_positive_float("KLOGS_REMOTE_TIMEOUT_S", 30.0)
        # --resolver: live membership (service/resolver.py). --remote
        # (when also given) is only the seed; the resolver's snapshots
        # take over from the first poll. A resolver alone may start
        # with an EMPTY seed — the first poll fills the fleet.
        live_resolver = None
        if resolver is not None:
            from klogs_tpu.service.resolver import make_resolver

            try:
                live_resolver = make_resolver(resolver,
                                              kubeconfig=kubeconfig)
            except ValueError as e:
                from klogs_tpu.service.client import ServiceConfigError

                raise ServiceConfigError(str(e)) from None
        targets = parse_endpoints(remote) if remote is not None else []
        from klogs_tpu.resilience import FAULTS

        stray = FAULTS.armed_targets() - set(targets)
        if stray and live_resolver is not None:
            # With live membership the fleet is open-ended: a targeted
            # clause naming a future joiner is legitimate chaos.
            stray = set()
        if stray:
            # A targeted chaos clause naming an endpoint outside the
            # fleet can never fire — one typoed digit and the chaos run
            # green-lights behavior it never exercised. Loud, like
            # every other bad-fault-spec path.
            term.warning(
                "KLOGS_FAULTS targets %s not in the --remote list %s — "
                "those clauses will never fire",
                ", ".join(sorted(stray)), ",".join(targets))
        from klogs_tpu.utils.env import read as env_read

        common = dict(
            tls_ca=env_read("KLOGS_REMOTE_TLS_CA"),
            tls_cert=env_read("KLOGS_REMOTE_TLS_CERT"),
            tls_key=env_read("KLOGS_REMOTE_TLS_KEY"),
            auth_token_file=env_read("KLOGS_REMOTE_TOKEN_FILE"),
            rpc_timeout_s=rpc_timeout_s,
            registry=registry)
        if len(targets) == 1 and live_resolver is None:
            # Single endpoint: the plain client, byte-identical to the
            # pre-shard behavior (no hedge tasks, no prober). With a
            # resolver even a single seed takes the sharded tier — the
            # fleet can grow past it.
            service = RemoteFilterClient(targets[0], **common)
        else:
            # A fleet: the sharded tier (docs/RESILIENCE.md, "Sharded
            # tier"). A batch raises Unavailable — and hence degrades
            # per --on-filter-error — only when EVERY endpoint is down.
            service = ShardedFilterClient(
                targets,
                shard_mode=shard_mode,
                fingerprint=pattern_fingerprint(patterns, exclude,
                                                ignore_case),
                hedge_s=_env_positive_float("KLOGS_HEDGE_S",
                                            DEFAULT_HEDGE_S),
                probe_interval_s=_env_positive_float(
                    "KLOGS_READYZ_INTERVAL_S", DEFAULT_PROBE_INTERVAL_S),
                resolver=live_resolver,
                **common)
        return FilterPipeline(
            log_filter=None,
            stats=stats,
            batch_lines=batch_lines or 8192,
            deadline_s=deadline_s,
            service=service,
            patterns=patterns,
            ignore_case=ignore_case,
            exclude=exclude,
            on_filter_error=on_filter_error,
        )
    if backend not in ("cpu", "tpu"):
        raise ValueError(f"unknown filter backend {backend!r}")
    from klogs_tpu.filters.base import build_include_exclude

    # Stats ride the include side only (or the combiner's inputs would
    # double-count); a both-empty call raises in the combinator instead
    # of building a pipeline that crashes on first use.
    made = []

    def builder(pats):
        f = _build_filter(pats, backend, stats if not made else None,
                          ignore_case)
        made.append(f)
        return f

    log_filter: LogFilter = build_include_exclude(builder, patterns, exclude)
    if backend == "cpu":
        batch_lines = batch_lines or 1024
    else:
        from klogs_tpu.filters.async_service import AsyncFilterService

        # Device batches are cheap per line but each round trip has fixed
        # latency: bigger batches + the async pipeline hide it.
        batch_lines = batch_lines or 8192
        service = AsyncFilterService(log_filter, stats=stats)
    return FilterPipeline(
        log_filter=log_filter,
        stats=stats,
        batch_lines=batch_lines,
        deadline_s=deadline_s,
        service=service,
        on_filter_error=on_filter_error,
    )
