"""NFAEngineFilter — the ``--backend=tpu`` LogFilter.

Host-side half of the TPU path: frames incoming lines into fixed-width
``[batch, max_line_bytes]`` uint8 tensors (the LineBatcher role from
SURVEY.md §2), ships them to the JAX engine (klogs_tpu.ops.nfa), and
returns the per-line keep-mask that gates file writes — the stage the
north star inserts at the reference's write boundary
(/root/reference/cmd/root.go:359-374).

Static-shape discipline (XLA traces once per shape): lines are padded
into power-of-two length buckets so the jit cache stays tiny; lines
longer than ``chunk_bytes`` run through the carried-state chunk path
(klogs_tpu.ops.nfa.match_chunk) instead of forcing a giant pad width —
the long-context design from SURVEY.md §5.

Trailing-newline handling matches RegexFilter: trailing "\\n" bytes are
stripped before matching, so ``$`` sees the logical end of line.
"""

import threading

import numpy as np

from klogs_tpu.filters.base import LogFilter
from klogs_tpu.filters.compiler.glushkov import compile_patterns
from klogs_tpu.utils.env import read as env_read

# Smallest pad width; also the bucket floor. 128 matches the TPU lane.
MIN_BUCKET = 128
# Smallest batch-dimension bucket. Both axes are padded to power-of-two
# buckets so XLA traces O(log) distinct shapes, not one per flush size.
MIN_BATCH_BUCKET = 8


def _bucket_len(n: int, chunk_bytes: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return min(b, chunk_bytes)


def _bucket_batch(n: int) -> int:
    b = MIN_BATCH_BUCKET
    while b < n:
        b *= 2
    return b


def classify_batch(batch: np.ndarray, lengths: np.ndarray, table: np.ndarray,
                   begin_c: int, end_c: int, pad_c: int) -> np.ndarray:
    """Vectorized host classification of an ALREADY-packed [B, L] u8
    batch into the [B, L+3] sentinel cls layout (see pack_classify) —
    exactly the first=True/final=True case of the chunk protocol, so
    the sentinel layout lives in one place (classify_chunk_host).
    Shared by the numpy pack_classify fallback and MeshEngine's
    batch->cls adapter."""
    return classify_chunk_host(batch, lengths, table, begin_c, end_c, pad_c,
                               first=True, final=True)


def classify_chunk_host(chunk: np.ndarray, rem: np.ndarray, table: np.ndarray,
                        begin_c: int, end_c: int, pad_c: int,
                        first: bool, final: bool) -> np.ndarray:
    """Host mirror of ops.nfa.classify_chunk (+ the final accept-latch
    column) for the carried-state long-line protocol: [B, L] u8 chunk +
    remaining-lengths -> [B, T] class ids. Same END-deferral semantics:
    END is emitted at chunk-local position ``rem`` when it falls inside
    this chunk's window (the final chunk gets one extra column so END
    can land at L), positions past END are PAD."""
    B, L = chunk.shape
    Lb = L + (1 if final else 0)
    T = Lb + (1 if first else 0) + (1 if final else 0)
    off = 1 if first else 0
    from klogs_tpu.native import hostops

    if (hostops is not None and hasattr(hostops, "classify_chunk")
            and table.dtype == np.int8 and chunk.dtype == np.uint8
            and chunk.flags.c_contiguous):
        buf = hostops.classify_chunk(
            chunk, B, L, rem.astype(np.int32).tobytes(), table.tobytes(),
            begin_c, end_c, pad_c, int(first), int(final))
        return np.frombuffer(buf, dtype=np.int8).reshape(B, T)
    cls = np.empty((B, T), dtype=table.dtype)
    if first:
        cls[:, 0] = begin_c
    if final:
        cls[:, off + L :] = pad_c  # extra END window col + latch col
    body = cls[:, off : off + L]
    # All-i8 operations (a nested where promotes to int64 and triples
    # the passes — measured 70 MB/s vs GB/s for this form).
    pos = np.arange(L, dtype=np.int32)[None, :]
    remc = rem.astype(np.int32)
    body[:] = table[chunk]
    body[pos >= remc[:, None]] = pad_c
    # END lands at chunk-local position rem when inside this chunk's
    # window (the final chunk's window includes position L).
    inside = (remc >= 0) & (remc < Lb)
    rows = np.nonzero(inside)[0]
    cls[rows, off + remc[rows]] = end_c
    return cls


def pack_classify(lines: list[bytes], width: int, table: np.ndarray,
                  begin_c: int, end_c: int, pad_c: int) -> np.ndarray:
    """[B] bytes -> [B', width+3] i8 class ids (B' batch-bucketed):
    col 0 BEGIN, cols 1..len table[byte], col len+1 END, rest PAD (the
    accept-latch column included). Fused pack + classification on the
    host — the device-side classify gather measured as ~85% of hot-path
    device time (BENCH_DEVICE.json "host_classify" probe), so the
    byte->class mapping happens here, in the native packer when built,
    else via vectorized numpy."""
    B = len(lines)
    rows = _bucket_batch(B)
    from klogs_tpu.native import hostops

    if hostops is not None and hasattr(hostops, "pack_classify"):
        buf, _lens = hostops.pack_classify(
            lines, width, rows, table.tobytes(), begin_c, end_c, pad_c)
        return np.frombuffer(buf, dtype=np.int8).reshape(rows, width + 3)
    batch, lengths = pack_lines(lines, width)
    return classify_batch(batch, lengths, table, begin_c, end_c, pad_c)


def pack_lines(lines: list[bytes], width: int) -> tuple[np.ndarray, np.ndarray]:
    """[B] bytes -> ([B', width] u8 zero-padded, [B'] i32 lengths) with
    B' = B rounded up to a batch bucket; pad rows are empty lines whose
    verdicts the caller slices off.

    Zero-padding bytes are ignored by the engine (positions >= length
    classify as pad_class), so the fill value is arbitrary. Uses the
    native packer (klogs_tpu.native) when available — the pure-Python
    per-line loop is the host-side bottleneck otherwise.
    """
    B = len(lines)
    rows = _bucket_batch(B)
    from klogs_tpu.native import hostops

    if hostops is not None:
        buf, lens = hostops.pack_lines(lines, width, rows)
        batch = np.frombuffer(buf, dtype=np.uint8).reshape(rows, width)
        return batch, np.frombuffer(lens, dtype=np.int32)
    batch = np.zeros((rows, width), dtype=np.uint8)
    lengths = np.zeros((rows,), dtype=np.int32)  # pad rows: empty lines
    for i, ln in enumerate(lines):
        lengths[i] = len(ln)
        batch[i, : len(ln)] = np.frombuffer(ln, dtype=np.uint8)
    return batch, lengths


class NFAEngineFilter(LogFilter):
    """Batch-NFA filter on the JAX engine (TPU when available, else the
    same code path on CPU — semantics are identical, per conftest's
    hermetic setup)."""

    # Above this, a single line routes to the sequence-parallel scan
    # (ops/seqscan): the chunked vector path costs len/chunk_bytes
    # SEQUENTIAL device dispatches, which for one huge line is pure
    # latency; the transfer-matrix tree turns it into batched matmuls.
    SEQ_SCAN_BYTES = 128 * 1024

    def __init__(self, patterns: list[str], ignore_case: bool = False,
                 chunk_bytes: int = 4096, engine=None, kernel: str | None = None,
                 stats=None):
        import jax

        from klogs_tpu.ops import nfa  # deferred: --backend=cpu must not need jax

        self._nfa = nfa
        self._prog = compile_patterns(patterns, ignore_case=ignore_case)
        self._dp = nfa.pack_program(self._prog)
        self._chunk_bytes = chunk_bytes
        self._engine = engine  # optional parallel engine (klogs_tpu.parallel)
        self._stats = stats  # optional FilterStats for engine visibility
        # Degrade flags and the jit-shape set are written by fetch-time
        # retry closures running in AsyncFilterService's executor
        # threads while the loop thread dispatches — mutations go under
        # this lock (declared in the lock-discipline table,
        # tools/analysis). Reads stay lock-free: a stale read of a
        # monotonic degrade flag only delays the fallback one batch.
        self._state_lock = threading.Lock()
        # Batch geometries already traced: a new (width, rows) pair is
        # one jit compile — surfaced as a compile-event counter so an
        # operator can see shape churn (each event is a latency cliff).
        self._shapes_seen: set[tuple[int, int]] = set()

        # Execution path for the hot op: the Pallas kernel on real TPU,
        # the jnp/lax.scan path elsewhere (identical semantics; the
        # kernel's Mosaic lowering needs TPU hardware). "interpret"
        # exercises the kernel code hermetically (tests).
        kernel = kernel or env_read("KLOGS_TPU_KERNEL", "auto")
        if kernel == "auto":
            kernel = "pallas" if jax.default_backend() not in ("cpu",) else "jnp"
        self._kernel = kernel
        if kernel in ("pallas", "interpret"):
            import jax.numpy as jnp

            from klogs_tpu.ops import pallas_nfa

            self._pallas = pallas_nfa
            # Full-line batches run the grouped kernel (patterns binned
            # into 128-state automata: MXU cost linear, not quadratic,
            # in total positions); the long-line chunk path uses the
            # single augmented union automaton (state carry across
            # chunks needs one uniform state space).
            self._dp_grouped, self._g_live, self._g_acc = nfa.compile_grouped(
                patterns, ignore_case=ignore_case
            )
            aug = nfa.augment(self._prog)
            self._dp_aug = nfa.pack_program(aug, dtype=jnp.int8)
            self._live = self._prog.n_states
            self._acc = self._prog.n_states + 1
            # Host-side classification table for the grouped hot path
            # (pack_classify). Class ids ride int8, so a pattern set
            # whose shared classifier exceeds 127 classes (hundreds of
            # byte-set-diverse patterns) falls back to device-side
            # classification rather than overflowing.
            if self._dp_grouped.n_classes <= 127:
                self._cls_table = np.asarray(
                    self._dp_grouped.byte_class).astype(np.int8)
            else:
                self._cls_table = None
            # Same for the augmented union program (long-line chunks).
            if self._dp_aug.n_classes <= 127:
                self._aug_cls_table = np.asarray(
                    self._dp_aug.byte_class).astype(np.int8)
            else:
                self._aug_cls_table = None
            # Degrade memory for the DEFAULTED chain variant
            # (mask_block=4 on hardware): chain restructurings are
            # compile-fragile on unproven backends (mask_block=8/16
            # fail Mosaic on v5e), so a default-variant failure flips
            # this and the engine continues on the plain chain. An
            # env-forced variant stays loud.
            self._chain_fallback = False
            # Two-phase filter: a mandatory-pair candidate mask gates
            # which kernel tiles run (ops/pallas_nfa skip-tiles path).
            # Default OFF: the 2026-07-29 device A/B (BENCH_DEVICE.json)
            # measured the byte-LUT candidate mask at ~684k lines/s —
            # nearly the full NFA kernel's cost — so gating was a net
            # loss (413k gated vs 641k plain). KLOGS_TPU_PREFILTER=1
            # opts in; requires every pattern to yield clauses.
            self._pf_tables = None
            if env_read("KLOGS_TPU_PREFILTER", "0") == "1":
                from klogs_tpu.filters.compiler.prefilter import compile_prefilter
                from klogs_tpu.ops.prefilter import class_tables, device_tables

                pf = compile_prefilter(patterns, ignore_case=ignore_case)
                if pf.usable:
                    # Class-domain tables (MXU matmul mask over the
                    # kernel's cls array); byte-LUT fallback only if the
                    # classifier were ever non-uniform w.r.t. the LUTs.
                    self._pf_tables = (
                        class_tables(pf, self._dp_grouped.byte_class,
                                     self._dp_grouped.n_classes)
                        or device_tables(pf)
                    )
                else:
                    # One clause-less pattern disables gating for the
                    # whole set (its candidate mask would be all-True);
                    # say so instead of failing silently.
                    from klogs_tpu.ui import term

                    culprits = [p for p, n in zip(patterns,
                                                  pf.clause_counts or [])
                                if n == 0]
                    if culprits:
                        reason = ("prefilter disabled: no mandatory byte "
                                  "pairs for pattern(s) %s" %
                                  ", ".join(repr(p) for p in culprits[:4]))
                    else:
                        # Every pattern HAS clauses; the shared slot
                        # table filled up before some pattern got one.
                        reason = ("prefilter disabled: clause slot table "
                                  "exhausted (pattern set too diverse)")
                    term.info("%s", reason)
                    if self._stats is not None:
                        self._stats.pf_disabled_reason = reason
            # Thousand-pattern fused path: the device literal sweep
            # (ops/sweep.py) gates (tile, group) kernel grid cells with
            # the factor-index candidate mask, computed ON DEVICE in
            # the same dispatch (frame -> sweep -> gated match, no host
            # round-trip). Auto at the same K threshold that flips
            # best_host_filter to the indexed engine, and only on a
            # real accelerator — on the CPU backend the dense sweep is
            # gather-bound and loses to the host sweep (BENCH_SWEEP).
            self._sweep_tables = None
            if engine is None:
                self._init_sweep(patterns, ignore_case)
        else:
            self._sweep_tables = None
            from klogs_tpu.filters.cpu import device_sweep_env

            if engine is None and device_sweep_env() == "1":
                # The fused sweep only exists for the pallas/interpret
                # kernels; a forced knob silently doing nothing here
                # would be the exact unexplained-~10x the validation
                # exists to prevent.
                from klogs_tpu.ui import term

                term.info(
                    "KLOGS_TPU_SWEEP=1 ignored: the fused sweep needs "
                    "the pallas/interpret kernel (running %s)",
                    kernel)

    def _init_sweep(self, patterns: list[str], ignore_case: bool) -> None:
        """Build the device sweep tables when the auto rule (or
        KLOGS_TPU_SWEEP=1) selects the fused path. Any build failure
        degrades LOUDLY to the plain kernel — same contract as the
        indexed-engine auto fallback in best_host_filter. The
        sweep-vs-prefilter precedence itself lives in ONE place shared
        with the mesh (cpu.device_gate_choice): the kernel accepts one
        gate only, an explicit prefilter opt-in beats the auto sweep,
        a forced sweep beats the prefilter — but the working prefilter
        is only discarded AFTER the tables actually build (a failed
        build must not leave the engine with neither gate)."""
        from klogs_tpu.filters.cpu import device_gate_choice
        from klogs_tpu.ui import term

        choice = device_gate_choice(
            len(patterns), have_prefilter=self._pf_tables is not None,
            interpret=self._kernel == "interpret")
        if choice != "sweep":
            return
        pg = self._dp_grouped.pattern_group
        if not pg:
            term.warning(
                "device sweep unavailable: grouped program carries no "
                "pattern_group map; running the plain kernel")
            return
        try:
            from klogs_tpu.filters.compiler.groups import analyze, plan_groups
            from klogs_tpu.filters.compiler.index import FactorIndex
            from klogs_tpu.ops.sweep import device_sweep_tables

            infos = analyze(patterns, ignore_case=ignore_case)
            index = FactorIndex(infos, plan_groups(infos))
            prog = index.sweep_program(
                group_of=np.asarray(pg, dtype=np.int32),
                n_groups=int(self._dp_grouped.follow.shape[0]))
            tables = device_sweep_tables(prog)
            if self._pf_tables is not None:
                from klogs_tpu.filters.cpu import note_sweep_supersedes

                note_sweep_supersedes()
            with self._state_lock:
                self._pf_tables = None
                self._sweep_tables = tables
        except Exception as e:
            # Auto/forced sweep failing to BUILD must not kill the
            # engine: the plain kernel is always correct — but say so,
            # a silent fallback at this K is an unexplained ~10x.
            term.warning(
                "device sweep build failed for this %d-pattern set "
                "(%s: %s); running the plain kernel",
                len(patterns), type(e).__name__, e)
            if self._stats is not None:
                self._stats.record_sweep_fallback()

    def match_lines(self, lines: list[bytes]) -> list[bool]:
        return self.fetch(self.dispatch(lines))

    def _record_sub_batch(self, width: int, rows: int,
                          payload_bytes: int) -> None:
        """Engine-layer instrumentation per width-bucketed sub-batch:
        bucket-width distribution, padding waste, and first-seen shape
        (≈ jit compile) events. No-op without a stats object."""
        if self._stats is None:
            return
        self._stats.record_engine_batch(width, rows, payload_bytes)
        key = (width, rows)
        with self._state_lock:
            first_seen = key not in self._shapes_seen
            self._shapes_seen.add(key)
        if first_seen:
            self._stats.record_compile()

    def _cls_args(self):
        """(table, begin, end, pad) for the active host-classify path."""
        if self._engine is not None:
            eng = self._engine
            return (eng.cls_table, eng.begin_class, eng.end_class,
                    eng.pad_class)
        dpg = self._dp_grouped
        return (self._cls_table, dpg.begin_class, dpg.end_class,
                dpg.pad_class)

    def _use_cls(self) -> bool:
        if self._engine is not None:
            # A mesh engine running the fused sweep consumes raw bytes.
            return (getattr(self._engine, "cls_table", None) is not None
                    and not getattr(self._engine, "swept", False))
        if getattr(self, "_sweep_tables", None) is not None:
            # The fused sweep consumes raw bytes (the cls hot path
            # never ships them to the device); short lines take the
            # byte-consuming grouped entry instead.
            return False
        return (self._kernel in ("pallas", "interpret")
                and getattr(self, "_cls_table", None) is not None)

    def dispatch_framed(self, payload: bytes, offsets):
        """Framed-batch dispatch: no per-line PyBytes on the hot path.
        Rows are width-bucketed vectorized (numpy over the offsets), each
        bucket packs straight out of the contiguous payload — via the C
        framed packer on the cls hot path, via the shared
        ``pack_framed_rows`` ragged scatter on the byte path (active
        device sweep, which consumes raw bytes; deferred from PR 8 —
        this entry used to detour through split_frame's per-line
        PyBytes there). Long/huge rows (rare) bridge to the chunked /
        seq-scan paths via slicing."""
        import numpy as np

        from klogs_tpu.native import hostops

        offsets = np.ascontiguousarray(offsets, dtype=np.int32)
        n = len(offsets) - 1
        if n == 0:
            return (0, [])
        if self._prog.match_all:
            return (n, None)
        if (hostops is not None
                and hasattr(hostops, "pack_classify_framed")
                and self._use_cls()):
            return self._dispatch_framed_cls(payload, offsets, n)
        if self._frames_bytes():
            return self._dispatch_framed_bytes(payload, offsets, n)
        from klogs_tpu.filters.base import split_frame

        return self.dispatch(split_frame(payload, offsets))

    def _frames_bytes(self) -> bool:
        """True when the active execution path consumes raw byte
        batches AND the framed byte packer should feed it directly:
        the fused device sweep (single-chip tables or a swept mesh
        engine) — its kernel takes bytes, so the cls packer cannot
        serve it and split_frame would cost n PyBytes per flush."""
        if getattr(self, "_sweep_tables", None) is not None:
            return True
        eng = self._engine
        return eng is not None and getattr(eng, "swept", False)

    def _framed_width_buckets(self, lens, short, n: int):
        """Power-of-two width bucket per row (jit-cache discipline,
        same buckets as the list path: every assignment clamps to
        chunk_bytes exactly like _bucket_len, or a non-power-of-two
        chunk_bytes would mint an EXTRA jit shape above it and pad
        every top-bucket row past the chunk width)."""
        import numpy as np

        chunk = self._chunk_bytes
        width_of = np.full(n, min(MIN_BUCKET, chunk), dtype=np.int64)
        w = MIN_BUCKET
        while w < chunk and bool((short & (lens > w)).any()):
            w *= 2
            width_of[lens > w // 2] = min(w, chunk)
        return width_of

    def _dispatch_framed_cls(self, payload: bytes, offsets, n: int):
        """The cls hot path: C framed packer -> class ids -> kernel.
        Raw lengths may include a trailing newline the C packer strips
        — the only effect is an occasional one-bucket-up pad, never a
        wrong width."""
        import numpy as np

        from klogs_tpu.native import hostops
        from klogs_tpu.obs import trace

        lens = np.diff(offsets)
        parts = []
        short = lens <= self._chunk_bytes
        if short.any():
            width_of = self._framed_width_buckets(lens, short, n)
            tab, bc, ec, pc = self._cls_args()
            tab_b = tab.tobytes()
            for w in np.unique(width_of[short]):
                sel = np.nonzero(short & (width_of == w))[0].astype(np.int32)
                rows = _bucket_batch(len(sel))
                with trace.TRACER.span("device.frame", width=int(w),
                                       rows=rows, path="cls"):
                    buf, _ = hostops.pack_classify_framed(
                        payload, offsets, n, sel.tobytes(), int(w),
                        rows, tab_b, bc, ec, pc)
                    cls = np.frombuffer(buf, dtype=np.int8).reshape(
                        -1, int(w) + 3)
                self._record_sub_batch(int(w), rows, int(lens[sel].sum()))
                # device.kernel times the (asynchronous) dispatch
                # enqueue; the round-trip completion is device.fetch.
                with trace.TRACER.span("device.kernel", width=int(w),
                                       rows=rows):
                    parts.append((sel, *self._match_cls_device(cls)))
        if not bool(short.all()):
            rest = np.nonzero(~short)[0]
            bodies = {int(i): payload[offsets[i]:offsets[i + 1]]
                      .rstrip(b"\n") for i in rest}
            self._dispatch_framed_rest(rest, bodies, parts)
        return (n, parts)

    def _dispatch_framed_bytes(self, payload: bytes, offsets, n: int):
        """The byte path (fused device sweep): width-bucketed [B, W] u8
        batches packed straight from the contiguous payload by the
        shared ``pack_framed_rows`` ragged scatter (filters/base), so
        the sweep path pays no per-line PyBytes either. Trailing
        newlines are peeled vectorized (rstrip parity with dispatch)."""
        import numpy as np

        from klogs_tpu.filters.base import pack_framed_rows
        from klogs_tpu.obs import trace

        starts = offsets[:-1].astype(np.int64)
        ends = offsets[1:].astype(np.int64).copy()
        if len(payload):
            arr = np.frombuffer(payload, dtype=np.uint8)
            while True:
                # Loop count = the longest trailing-newline run
                # (almost always 1); each pass is one vectorized scan.
                m = (ends > starts) & (arr[np.maximum(ends, 1) - 1] == 0x0A)
                if not bool(m.any()):
                    break
                ends[m] -= 1
        lens = ends - starts
        parts = []
        short = lens <= self._chunk_bytes
        if bool(short.any()):
            width_of = self._framed_width_buckets(lens, short, n)
            for w in np.unique(width_of[short]):
                sel = np.nonzero(short & (width_of == w))[0]
                rows = _bucket_batch(len(sel))
                with trace.TRACER.span("device.frame", width=int(w),
                                       rows=rows, path="bytes"):
                    batch, sub_lens = pack_framed_rows(
                        payload, offsets, int(w), rows=rows, sel=sel,
                        lens=lens[sel])
                lengths = np.zeros(rows, dtype=np.int32)
                lengths[:len(sel)] = sub_lens
                self._record_sub_batch(int(w), rows, int(lens[sel].sum()))
                with trace.TRACER.span("device.kernel", width=int(w),
                                       rows=rows, swept=True):
                    parts.append((sel, *self._match_full(batch, lengths)))
        if not bool(short.all()):
            rest = np.nonzero(~short)[0]
            bodies = {int(i): payload[int(starts[i]):int(ends[i])]
                      for i in rest}
            self._dispatch_framed_rest(rest, bodies, parts)
        return (n, parts)

    def _dispatch_framed_rest(self, rest, bodies: dict, parts: list) -> None:
        """Long/huge rows shared by both framed paths: bridge to the
        carried-state chunk path / seq-scan via the (already stripped)
        body slices."""
        long_idx = [int(i) for i in rest
                    if len(bodies[int(i)]) <= self.SEQ_SCAN_BYTES]
        huge_idx = [int(i) for i in rest
                    if len(bodies[int(i)]) > self.SEQ_SCAN_BYTES]
        if long_idx:
            parts.append((long_idx, self._match_long(
                [bodies[i] for i in long_idx]), None, None))
        if huge_idx:
            parts.append((huge_idx, self._match_huge(
                [bodies[i] for i in huge_idx]), None, None))

    def dispatch(self, lines: list[bytes]):
        """Enqueue device work for a batch WITHOUT blocking on results
        (jax dispatch is asynchronous). Returns a handle for fetch()."""
        if not lines:
            return (0, [])
        if self._prog.match_all:
            return (len(lines), None)  # all-match shortcut
        bodies = [ln.rstrip(b"\n") for ln in lines]  # parity with RegexFilter
        parts = []  # (index_list, device_mask_or_ndarray)

        short_idx = [i for i, b in enumerate(bodies) if len(b) <= self._chunk_bytes]
        long_idx = [i for i, b in enumerate(bodies)
                    if self._chunk_bytes < len(b) <= self.SEQ_SCAN_BYTES]
        huge_idx = [i for i, b in enumerate(bodies) if len(b) > self.SEQ_SCAN_BYTES]

        # Bucket short lines by padded width to bound jit-cache churn.
        buckets: dict[int, list[int]] = {}
        for i in short_idx:
            buckets.setdefault(
                _bucket_len(len(bodies[i]), self._chunk_bytes), []
            ).append(i)
        # MeshEngine exposes its global classifier when class ids fit
        # int8 — the multi-chip hot path takes cls directly; an active
        # device sweep forces the byte path instead (_use_cls).
        use_cls = self._use_cls()
        for width, idxs in buckets.items():
            sub = [bodies[i] for i in idxs]
            self._record_sub_batch(width, _bucket_batch(len(sub)),
                                   sum(len(b) for b in sub))
            if use_cls:
                parts.append((idxs, *self._match_cls_dispatch(sub, width)))
            else:
                batch, lengths = pack_lines(sub, width)
                parts.append((idxs, *self._match_full(batch, lengths)))
        if long_idx:
            parts.append(
                (long_idx, self._match_long([bodies[i] for i in long_idx]),
                 None, None))
        if huge_idx:
            parts.append(
                (huge_idx, self._match_huge([bodies[i] for i in huge_idx]),
                 None, None))
        return (len(lines), parts)

    def fetch(self, handle) -> list[bool]:
        return self._fetch_array(handle).tolist()

    def fetch_framed(self, handle) -> np.ndarray:
        return self._fetch_array(handle)

    def _fetch_array(self, handle) -> np.ndarray:
        """Block until the dispatched batch's verdicts are on host.

        An asynchronously-failing device batch (e.g. OOM at execution)
        surfaces HERE, not at dispatch — when the failing part carries a
        retry closure (the gated-kernel path), the failure degrades to
        the plain kernel instead of killing the streaming run."""
        n, parts = handle
        if parts is None:
            return np.ones(n, dtype=bool)
        out = np.zeros(n, dtype=bool)
        for idxs, mask, retry, pf in parts:
            try:
                vals = np.asarray(mask)
            except Exception as e:
                if retry is None:
                    raise
                from klogs_tpu.ui import term

                term.warning(
                    "device kernel failed at fetch (%s); "
                    "retrying on the plain path", str(e)[:120])
                vals = np.asarray(retry())
                pf = None
            out[idxs] = vals[: len(idxs)]
            if pf is not None and self._stats is not None:
                swept = isinstance(pf, tuple) and pf and pf[0] == "sweep"
                if swept:
                    pf = pf[1]
                n_cand, n_live, n_tiles = (int(np.asarray(x)) for x in pf)
                self._stats.record_prefilter(
                    len(idxs), min(n_cand, len(idxs)), n_tiles, n_live)
                if swept:
                    self._stats.record_sweep(
                        "device", len(idxs), min(n_cand, len(idxs)))
        return out

    def _match_cls_dispatch(self, bodies: list[bytes], width: int):
        """Hot path: host-side fused pack+classify, device kernel on
        class ids (no classify gather on device). Returns
        (device_mask, retry_closure_or_None, pf_stats_or_None)."""
        tab, bc, ec, pc = self._cls_args()
        cls = pack_classify(bodies, width, tab, bc, ec, pc)
        return self._match_cls_device(cls)

    def _match_cls_device(self, cls: np.ndarray):
        """Device half of the cls hot path — shared by the list and
        framed packers. Returns (device_mask, retry_or_None,
        pf_stats_or_None)."""
        if self._engine is not None:
            eng = self._engine
            retry = None
            if getattr(eng, "gated", False):
                # Degrade path for an opt-in gated kernel that fails
                # asynchronously: fetch() retries on the plain fn (whose
                # own sync chain-degrade then covers a chain fault).
                def retry(cls=cls):
                    eng.disable_prefilter()
                    return eng.match_cls(cls, plain=True)
            elif getattr(eng, "_chain_defaulted", False):
                # No gating, but the DEFAULTED chain variant can still
                # fail asynchronously at fetch: degrade and rerun.
                def retry(cls=cls):
                    eng.degrade_chain()
                    return eng.match_cls(cls)
            try:
                return eng.match_cls(cls), retry, None
            except Exception as e:
                if retry is None:
                    raise
                from klogs_tpu.ui import term

                term.warning(
                    "gated mesh kernel unavailable (%s); "
                    "falling back to plain NFA", str(e)[:120])
                return retry(), None, None
        dpg = self._dp_grouped
        interpret = self._kernel == "interpret"
        kw, chain_defaulted = self._chain_kwargs(interpret)

        def run_plain(run_kw):
            return self._pallas.match_cls_grouped_pallas(
                dpg, self._g_live, self._g_acc, cls,
                interpret=interpret, **run_kw)

        def chain_retry(record: bool = True):
            # Rerun without the chain restructure ONLY if the chain was
            # a default — an env-forced variant is kept even here (the
            # operator asked to measure exactly that kernel; if it is
            # the async fault the rerun fails again and raises loudly).
            if record and chain_defaulted:
                with self._state_lock:
                    self._chain_fallback = True
            return run_plain(dict(kw, mask_block=1) if chain_defaulted
                             else kw)

        def pf_retry(record: bool = True):
            # Fetch-time failure of the PREFILTERED kernel: degrade one
            # cause at a time (ADVICE r4) — drop gating but KEEP the
            # defaulted chain variant (its +13% win is independent of
            # the prefilter); only degrade the chain if the plain rerun
            # also fails. np.asarray forces the rerun synchronous so a
            # second async fault surfaces here, not at the caller.
            with self._state_lock:
                self._pf_tables = None
            try:
                return np.asarray(run_plain(kw))
            except Exception as e:
                if not chain_defaulted:
                    raise
                from klogs_tpu.ui import term

                term.warning(
                    "plain chain rerun also failed (%s); degrading to "
                    "mask_block=1", str(e)[:120])
                return chain_retry()

        if self._pf_tables is not None and len(self._pf_tables) == 4:
            want_stats = self._stats is not None
            try:
                res = self._pallas.match_cls_grouped_pallas(
                    dpg, self._g_live, self._g_acc, cls,
                    interpret=interpret,
                    prefilter_tables=self._pf_tables,
                    return_stats=want_stats, **kw)
                mask, pf = res if want_stats else (res, None)
                return mask, pf_retry, pf
            except Exception as e:
                # Gated-kernel compile trouble (Mosaic) must degrade to
                # the plain NFA, not kill the streaming run.
                from klogs_tpu.ui import term

                term.warning(
                    "prefiltered kernel unavailable (%s); "
                    "falling back to plain NFA", str(e)[:120])
                with self._state_lock:
                    self._pf_tables = None
        try:
            mask = run_plain(kw)
        except Exception as e:
            if not chain_defaulted:
                raise
            from klogs_tpu.ui import term

            term.warning(
                "default mask_block=%d chain failed on this backend (%s); "
                "continuing on the plain chain",
                kw.get("mask_block"), str(e)[:120])
            return chain_retry(), None, None
        # A defaulted chain variant can also fail ASYNCHRONOUSLY (device
        # execution surfaces at fetch); hand fetch() the same retry.
        return mask, (chain_retry if chain_defaulted else None), None

    def _chain_kwargs(self, interpret: bool):
        """(kernel kwargs, chain_defaulted): tune.chain_selection plus
        the degrade memory — after a default-variant failure every later
        batch runs the plain chain directly."""
        from klogs_tpu.ops.tune import chain_selection

        kw, defaulted, _ = chain_selection(on_hardware=not interpret)
        if self._chain_fallback and defaulted:
            kw["mask_block"] = 1
            defaulted = False
        return kw, defaulted

    def _match_full(self, batch: np.ndarray, lengths: np.ndarray):
        """Byte-consuming full-line path (device-side classify).
        Returns (device_mask, retry_or_None, sweep_stats_or_None) — the
        retry covers an ASYNC failure (defaulted chain variant or the
        fused sweep kernel) surfacing at fetch(), mirroring
        _match_cls_dispatch."""
        if self._engine is not None:
            eng = self._engine
            retry = None
            swept_before = getattr(eng, "swept", False)
            if swept_before:
                # Async failure of the fused sweep fn surfaces at
                # fetch: drop the sweep, count the degrade (the mesh
                # holds no stats handle), rerun on the classify path
                # (whose own gated/chain degrades then apply).
                def retry(batch=batch, lengths=lengths):
                    eng.disable_sweep()
                    if self._stats is not None:
                        self._stats.record_sweep_fallback()
                    return eng.match_batch(batch, lengths)
            elif getattr(eng, "gated", False):
                def retry(batch=batch, lengths=lengths):
                    eng.disable_prefilter()
                    return eng.match_batch(batch, lengths)
            elif getattr(eng, "_chain_defaulted", False):
                def retry(batch=batch, lengths=lengths):
                    eng.degrade_chain()
                    return eng.match_batch(batch, lengths)
            mask = eng.match_batch(batch, lengths)
            if (swept_before and not getattr(eng, "swept", False)
                    and self._stats is not None):
                # The mesh degraded internally at dispatch (its own
                # try/except warned already) — surface it on the
                # wrapper's counter so klogs_sweep_fallback_total is
                # the one place sweep degrades show.
                self._stats.record_sweep_fallback()
            return mask, retry, None
        if self._kernel in ("pallas", "interpret"):
            interpret = self._kernel == "interpret"
            kw, chain_defaulted = self._chain_kwargs(interpret)

            def plain_retry(record: bool = True):
                if record:
                    with self._state_lock:
                        self._chain_fallback = True
                return self._pallas.match_batch_grouped_pallas(
                    self._dp_grouped, self._g_live, self._g_acc,
                    batch, lengths, interpret=interpret,
                    **dict(kw, mask_block=1))

            def run_plain(run_kw):
                return self._pallas.match_batch_grouped_pallas(
                    self._dp_grouped, self._g_live, self._g_acc,
                    batch, lengths, interpret=interpret, **run_kw)

            sweep = getattr(self, "_sweep_tables", None)
            if sweep is not None:

                def sweep_retry(record: bool = True):
                    # Fetch-time failure of the FUSED sweep kernel:
                    # drop the sweep gate (one cause at a time — the
                    # chain variant is independent), record the
                    # degrade, rerun plain. np.asarray forces the rerun
                    # synchronous so a second async fault surfaces
                    # here.
                    with self._state_lock:
                        self._sweep_tables = None
                    if self._stats is not None:
                        self._stats.record_sweep_fallback()
                    try:
                        return np.asarray(run_plain(kw))
                    except Exception:
                        if not chain_defaulted:
                            raise
                        return plain_retry()

                want_stats = self._stats is not None
                try:
                    res = self._pallas.match_batch_grouped_pallas(
                        self._dp_grouped, self._g_live, self._g_acc,
                        batch, lengths, interpret=interpret,
                        sweep_tables=sweep, return_stats=want_stats,
                        **kw)
                    mask, sw = res if want_stats else (res, None)
                    return (mask, sweep_retry,
                            None if sw is None else ("sweep", sw))
                except Exception as e:
                    from klogs_tpu.ui import term

                    term.warning(
                        "fused sweep kernel unavailable (%s); "
                        "falling back to plain NFA", str(e)[:120])
                    with self._state_lock:
                        self._sweep_tables = None
                    if self._stats is not None:
                        self._stats.record_sweep_fallback()
            try:
                mask = run_plain(kw)
            except Exception as e:
                if not chain_defaulted:
                    raise
                from klogs_tpu.ui import term

                term.warning(
                    "default mask_block=%d chain failed on this backend "
                    "(%s); continuing on the plain chain",
                    kw.get("mask_block"), str(e)[:120])
                return plain_retry(), None, None
            return mask, (plain_retry if chain_defaulted else None), None
        return self._nfa.match_batch(self._dp, batch, lengths), None, None

    def _match_long(self, bodies: list[bytes]) -> np.ndarray:
        """Carried-state chunked matching: all long lines advance in
        lockstep, the NFA state vector threaded across chunks."""
        L = self._chunk_bytes
        B = _bucket_batch(len(bodies))
        total = np.zeros(B, dtype=np.int32)
        total[: len(bodies)] = [len(b) for b in bodies]
        pad_rows = B - len(bodies)
        n_chunks = int(np.ceil(total.max() / L))
        use_pallas = self._kernel in ("pallas", "interpret")
        if use_pallas:
            v = self._pallas.initial_state_kernel(self._dp_aug, self._live, B)
        else:
            v, matched = self._nfa.initial_state(self._dp, B)
        host_cls = use_pallas and getattr(self, "_aug_cls_table", None) is not None
        for k in range(n_chunks):
            seg = [b[k * L : (k + 1) * L].ljust(L, b"\0") for b in bodies]
            seg += [b"\0" * L] * pad_rows
            chunk = np.frombuffer(b"".join(seg), dtype=np.uint8).reshape(B, L)
            rem = total - k * L
            first, final = (k == 0), (k == n_chunks - 1)
            if host_cls:
                # Host-side classification, like the full-line hot path
                # (the device classify gather is ~85% of device time).
                dpa = self._dp_aug
                cls = classify_chunk_host(
                    chunk, rem, self._aug_cls_table,
                    dpa.begin_class, dpa.end_class, dpa.pad_class,
                    first=first, final=final)
                v, matched = self._pallas.match_chunk_cls_pallas(
                    dpa, self._acc, cls, v, final=final,
                    interpret=(self._kernel == "interpret"),
                )
            elif use_pallas:
                v, matched = self._pallas.match_chunk_pallas(
                    self._dp_aug, self._acc, chunk, rem, v,
                    first=first, final=final,
                    interpret=(self._kernel == "interpret"),
                )
            else:
                v, matched = self._nfa.match_chunk(
                    self._dp, chunk, rem, v, matched,
                    first=first, final=final,
                )
        return matched  # device array (padded); fetch() slices on host

    def _match_huge(self, bodies: list[bytes]) -> np.ndarray:
        """Sequence-parallel scan (ops/seqscan): log-depth batched
        transfer-matrix composition instead of len/chunk sequential
        dispatches. Concurrent jumbo lines advance together in one
        vmapped program per chunk-count bucket — no per-line dispatch
        or recompilation."""
        import jax.numpy as jnp

        from klogs_tpu.ops import seqscan

        if not hasattr(self, "_dp_seq"):
            aug = self._nfa.augment(self._prog)
            self._dp_seq = self._nfa.pack_program(aug, dtype=jnp.int8)
            self._seq_live = self._prog.n_states
            self._seq_acc = self._prog.n_states + 1
        return np.array(
            seqscan.match_lines_scan(self._dp_seq, self._seq_live,
                                     self._seq_acc, bodies),
            dtype=bool)

    def close(self) -> None:
        if self._engine is not None:
            self._engine.close()
