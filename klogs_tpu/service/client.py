"""Remote filter client — plugs into FilteredSink's async `service` slot.

Satisfies the same awaitable-match protocol as AsyncFilterService, so a
collector can gate writes on a remote TPU process exactly as it would on
an in-process engine. RPCs pipeline naturally over one HTTP/2 channel
(each in-flight Match is its own stream), so concurrent sink flushes
overlap without extra machinery.
"""

import grpc

from klogs_tpu.service import transport


class PatternMismatch(RuntimeError):
    pass


class RemoteFilterClient:
    def __init__(self, target: str):
        self._target = target
        self._channel = grpc.aio.insecure_channel(target, options=[
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ])
        self._match_rpc = self._channel.unary_unary(transport.MATCH)
        self._hello_rpc = self._channel.unary_unary(transport.HELLO)

    async def hello(self) -> dict:
        return transport.unpack(await self._hello_rpc(b""))

    async def verify_patterns(self, patterns: list[str],
                              ignore_case: bool = False) -> None:
        """Fail fast if the server filters with a different pattern set
        (or case mode) than this collector was invoked with."""
        info = await self.hello()
        if list(info.get("patterns", [])) != list(patterns):
            raise PatternMismatch(
                f"filter service at {self._target} serves patterns "
                f"{info.get('patterns')!r}, collector wants {patterns!r}"
            )
        if bool(info.get("ignore_case", False)) != bool(ignore_case):
            raise PatternMismatch(
                f"filter service at {self._target} has ignore_case="
                f"{info.get('ignore_case', False)!r}, collector wants "
                f"{bool(ignore_case)!r}"
            )

    async def match(self, lines: list[bytes]) -> list[bool]:
        resp = await self._match_rpc(transport.encode_match_request(lines))
        return transport.decode_match_response(resp)

    async def aclose(self) -> None:
        """Graceful shutdown: awaited from the pipeline so the channel
        closes before the event loop exits (a fire-and-forget task here
        leaks and warns under an exiting loop)."""
        await self._channel.close()

    def close(self) -> None:
        # Sync fallback (non-async teardown paths only): schedule if a
        # loop is running, else the channel dies with the process.
        import asyncio

        try:
            loop = asyncio.get_running_loop()
            loop.create_task(self._channel.close())
        except RuntimeError:
            pass
