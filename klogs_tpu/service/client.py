"""Remote filter client — plugs into FilteredSink's async `service` slot.

Satisfies the same awaitable-match protocol as AsyncFilterService, so a
collector can gate writes on a remote TPU process exactly as it would on
an in-process engine. RPCs pipeline naturally over one HTTP/2 channel
(each in-flight Match is its own stream), so concurrent sink flushes
overlap without extra machinery.
"""

import grpc

from klogs_tpu.cluster.backend import ClusterError
from klogs_tpu.resilience import (
    CircuitBreaker,
    RetryPolicy,
    Unavailable,
    retry_call,
)
from klogs_tpu.service import transport

# Transient failure classes worth retrying: the server is restarting /
# the LB dropped the stream (UNAVAILABLE) or one attempt overran its
# per-attempt deadline (DEADLINE_EXCEEDED). Anything else — bad
# request, auth, resource exhaustion — retrying cannot fix.
_RETRYABLE_CODES = (grpc.StatusCode.UNAVAILABLE,
                    grpc.StatusCode.DEADLINE_EXCEEDED)

# Per-client defaults; override via constructor for library use.
DEFAULT_RETRY = RetryPolicy(max_attempts=4, base_s=0.25, max_s=5.0,
                            jitter=0.1)
DEFAULT_BREAKER_THRESHOLD = 5
DEFAULT_BREAKER_RESET_S = 10.0


def _retryable(e: BaseException) -> bool:
    return (isinstance(e, grpc.aio.AioRpcError)
            and e.code() in _RETRYABLE_CODES)


class PatternMismatch(RuntimeError):
    pass


class ShedByServer(Unavailable):
    """A multi-tenant filterd shed this batch over the set's quota
    (RESOURCE_EXHAUSTED). Subclasses Unavailable so it rides the
    existing --on-filter-error degrade path — a shed batch is a counted
    degrade event, never a silent drop — and the sharded tier treats it
    as a failover signal (a sibling may have quota headroom)."""


class SetEvicted(ClusterError):
    """The server no longer holds this client's registered set
    (FAILED_PRECONDITION: cold-set eviction or a server restart). The
    client re-registers once and retries; surfaced — as the CLI's
    friendly one-liner — only when that is impossible (no recorded
    expected config to re-register)."""


class ServiceConfigError(ValueError):
    """Invalid/partial transport-security configuration or unreadable
    credential material — surfaced as one friendly fatal line by the
    CLI, never a silent insecure fallback."""


def _read(path: str, what: str) -> bytes:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError as e:
        raise ServiceConfigError(f"cannot read {what} {path}: {e}") from e


def tenant_weight() -> float:
    """This collector's weighted-fair share request, sent with its
    Register RPC against a multi-set filterd (KLOGS_TENANT_WEIGHT,
    default 1.0 — equal shares). Highest registered weight wins for a
    shared set, server-side. Validated here: a bad value must fail
    naming the variable, not degrade to silent equal-share."""
    import math

    from klogs_tpu.utils.env import read as env_read

    raw = env_read("KLOGS_TENANT_WEIGHT")
    if raw is None:
        return 1.0
    try:
        v = float(raw)
        if not math.isfinite(v) or not 0 < v <= 1024:
            raise ValueError
    except ValueError:
        raise ServiceConfigError(
            f"KLOGS_TENANT_WEIGHT must be in (0, 1024], got {raw!r}"
        ) from None
    return v


def check_server_config(target: str, info: dict, patterns: list[str],
                        ignore_case: bool,
                        exclude: "list[str] | None") -> str:
    """Compare a Hello response against the collector's invocation.
    Returns ``"ok"`` (verified), or ``"register"`` when the server runs
    the multi-tenant registry and this collector's set must be (or
    already is) registered there — a multi-set server never "drifts",
    it registers, so the single-set PatternMismatch hard-fail only
    applies to fixed-set servers. Raises PatternMismatch naming
    ``target`` on single-set drift. Shared by the single-endpoint
    client and the sharded tier (which verifies every endpoint from ONE
    Hello each instead of re-dialing per check)."""
    if info.get("multi_set"):
        # Always (re-)register: it is content-addressed and idempotent
        # (a live set is a cheap reuse that refreshes the LRU clock),
        # and every client needs the returned set id to tag its match
        # RPCs — even when a sibling collector registered the set
        # first.
        return "register"
    if list(info.get("patterns", [])) != list(patterns):
        raise PatternMismatch(
            f"filter service at {target} serves patterns "
            f"{info.get('patterns')!r}, collector wants {patterns!r}"
        )
    if list(info.get("exclude", [])) != list(exclude or []):
        raise PatternMismatch(
            f"filter service at {target} has exclude patterns "
            f"{info.get('exclude')!r}, collector wants {exclude or []!r}"
        )
    if bool(info.get("ignore_case", False)) != bool(ignore_case):
        raise PatternMismatch(
            f"filter service at {target} has ignore_case="
            f"{info.get('ignore_case', False)!r}, collector wants "
            f"{bool(ignore_case)!r}"
        )
    return "ok"


class RemoteFilterClient:
    """``tls_ca`` switches the channel to TLS (server verified against
    that bundle); ``tls_cert``/``tls_key`` add a client certificate
    (mTLS). ``auth_token`` (or ``auth_token_file``, re-read per RPC so
    a rotated mounted Secret keeps working) attaches ``authorization:
    Bearer <token>`` metadata to every RPC. All default off — see
    FilterServer for the matching server-side knobs. Partial TLS
    configuration is an error, never a silent plaintext fallback; a
    bearer token over plaintext earns a warning (it travels in the
    clear)."""

    def __init__(self, target: str, tls_ca: str | None = None,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 auth_token: str | None = None,
                 auth_token_file: str | None = None,
                 retry: "RetryPolicy | None" = None,
                 breaker: "CircuitBreaker | None" = None,
                 rpc_timeout_s: "float | None" = 30.0,
                 registry=None):
        if (tls_cert or tls_key) and not tls_ca:
            raise ServiceConfigError(
                "tls_cert/tls_key (mTLS) require tls_ca — refusing to "
                "silently open an insecure channel")
        if bool(tls_cert) != bool(tls_key):
            raise ServiceConfigError(
                "tls_cert and tls_key must be provided together")
        if auth_token and auth_token_file:
            raise ServiceConfigError(
                "pass auth_token OR auth_token_file, not both")
        self._target = target
        if auth_token_file:
            _read(auth_token_file, "bearer token file")  # fail fast,
            # BEFORE any channel exists or warning prints
        options = [
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ]
        if tls_ca:
            creds = grpc.ssl_channel_credentials(
                root_certificates=_read(tls_ca, "TLS CA bundle"),
                private_key=_read(tls_key, "TLS client key") if tls_key else None,
                certificate_chain=_read(tls_cert, "TLS client cert")
                if tls_cert else None)
            self._channel = grpc.aio.secure_channel(target, creds,
                                                    options=options)
        else:
            if auth_token or auth_token_file:
                from klogs_tpu.ui import term

                term.warning(
                    "bearer token to %s travels over PLAINTEXT "
                    "(set KLOGS_REMOTE_TLS_CA to encrypt the hop)", target)
            self._channel = grpc.aio.insecure_channel(target, options=options)
        self._auth_token = auth_token
        self._auth_token_file = auth_token_file
        self._match_rpc = self._channel.unary_unary(transport.MATCH)
        self._match_framed_rpc = self._channel.unary_unary(
            transport.MATCH_FRAMED)
        self._hello_rpc = self._channel.unary_unary(transport.HELLO)
        self._register_rpc = self._channel.unary_unary(transport.REGISTER)
        # None until the first Hello; old servers (no "framed" key)
        # route match_framed through the legacy per-line Match.
        self._server_framed: bool | None = None
        # Sync close() parks its channel-close task here; aclose()
        # settles it so it can't outlive the client.
        self._close_task: "asyncio.Task | None" = None
        # Multi-tenant registry state (docs/TENANCY.md): the set id the
        # server handed back at registration, attached to every match
        # RPC; the expected config is remembered so an evicted set can
        # be re-registered transparently mid-stream.
        self._set_id: str | None = None
        self._expected_cfg: "tuple[list[str], bool, list[str]] | None" = None
        # Resilience (docs/RESILIENCE.md): every RPC runs under a
        # per-attempt Deadline + retry on UNAVAILABLE/DEADLINE_EXCEEDED
        # behind one breaker per client — consecutive failures trip it
        # and subsequent calls fast-fail (Unavailable), which the
        # FilteredSink routes per --on-filter-error instead of letting
        # a dead filterd wedge every sink flush. Breaker name and retry
        # site both carry the endpoint identity: against a sharded
        # --remote fleet, anonymous "rpc" series would merge every
        # server into one undebuggable line.
        self._retry = retry if retry is not None else DEFAULT_RETRY
        self._site = f"rpc@{target}"
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            name=self._site, failure_threshold=DEFAULT_BREAKER_THRESHOLD,
            reset_timeout_s=DEFAULT_BREAKER_RESET_S, registry=registry)
        self._rpc_timeout_s = rpc_timeout_s
        self._registry = registry

    @property
    def target(self) -> str:
        return self._target

    @property
    def breaker(self) -> CircuitBreaker:
        """This client's breaker — the sharded tier reads its state to
        route batches around an endpoint that is fast-failing."""
        return self._breaker

    def _metadata(self):
        token = self._auth_token
        if self._auth_token_file:
            # An unreadable token file names ITSELF as the failure — a
            # silent unauthenticated RPC would blame the server/token
            # value instead of the local path.
            token = _read(self._auth_token_file,
                          "bearer token file").decode().strip()
        return (("authorization", f"Bearer {token}"),) if token else None

    def _friendly(self, e: "grpc.aio.AioRpcError"):
        # One clean line instead of a grpc traceback: reuse the CLI's
        # ClusterError path (control-plane-failure UX, cli.py).
        return ClusterError(
            f"filter service at {self._target}: "
            f"{e.code().name}: {e.details()}")

    async def _call(self, rpc, request: bytes, fault_point: str):
        """One guarded RPC: breaker gate, fresh per-attempt Deadline,
        retry with jittered backoff on transient codes. A terminal
        transient failure (retries exhausted / breaker open) raises
        ``resilience.Unavailable`` — the type FilteredSink's
        --on-filter-error degrade routing catches; any other RPC error
        gets the friendly one-line ClusterError as before.

        The whole retry tower runs under one ``rpc.client`` span; the
        batch's trace context rides each attempt as gRPC metadata
        (transport.trace_metadata), so server-side spans parent under
        this one. A hedge loser's task is cancelled here mid-await and
        its span closes status=cancelled — the flight-recorder
        signature that distinguishes a lost race from a failure."""
        from klogs_tpu.obs.trace import TRACER

        async def attempt(deadline):
            md = tuple(self._metadata() or ()) + transport.trace_metadata()
            return await rpc(
                request, metadata=md or None,
                timeout=(deadline.remaining()
                         if deadline is not None else None))

        try:
            with TRACER.span("rpc.client", target=self._target,
                             method=fault_point) as sp:
                result = await retry_call(
                    attempt, policy=self._retry, retryable=_retryable,
                    site=self._site,
                    describe=f"filter service at {self._target}",
                    breaker=self._breaker, deadline_s=self._rpc_timeout_s,
                    fault_point=fault_point, fault_target=self._target,
                    registry=self._registry)
                sp.set_attr("response_bytes", len(result))
                return result
        except Unavailable as e:
            cause = e.__cause__
            if isinstance(cause, grpc.aio.AioRpcError):
                # str(AioRpcError) is a multi-line debug blob; keep the
                # pre-resilience one-line CODE: details form on the
                # degrade/fatal path.
                raise type(e)(
                    f"filter service at {self._target}: "
                    f"{cause.code().name}: {cause.details()} "
                    f"(retries exhausted)") from cause
            raise
        except grpc.aio.AioRpcError as e:
            if (e.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
                    and (e.details() or "").startswith(
                        transport.OVER_QUOTA)):
                # Multi-tenant quota shed (keyed on the wire token —
                # gRPC's own RESOURCE_EXHAUSTED for oversize messages
                # must stay a loud ClusterError): NOT retried (the
                # lane is full; an instant retry only deepens it) and
                # NOT a breaker failure — it flows to the degrade path
                # (or the shard tier's failover: a sibling may have
                # room).
                raise ShedByServer(
                    f"filter service at {self._target}: "
                    f"{e.details()}") from e
            if (e.code() == grpc.StatusCode.FAILED_PRECONDITION
                    and (e.details() or "").startswith(
                        transport.SET_NOT_REGISTERED)):
                # The server evicted (or never had) our set: the caller
                # re-registers once and retries. Keyed on the stable
                # wire token, not the prose after it (version skew).
                raise SetEvicted(
                    f"filter service at {self._target}: "
                    f"{e.details()}") from e
            raise self._friendly(e) from e

    async def hello(self) -> dict:
        # Once an expected config is recorded (verify_patterns /
        # ensure_registered), every Hello carries it: a multi-set
        # server then answers against its REGISTRY for OUR fingerprint
        # instead of its default set.
        body = b""
        if self._expected_cfg is not None:
            pats, ic, excl = self._expected_cfg
            body = transport.encode_hello_request(pats, excl, ic)
        info = transport.unpack(
            await self._call(self._hello_rpc, body, "rpc.hello"))
        self._server_framed = bool(info.get("framed", False))
        return info

    async def verify_patterns(self, patterns: list[str],
                              ignore_case: bool = False,
                              exclude: "list[str] | None" = None) -> None:
        """Fail fast if the server filters with a different pattern set
        (case mode or exclude set) than this collector was invoked
        with. Against a multi-tenant registry server there is no fixed
        set to drift from: the collector REGISTERS its set instead
        (content-addressed — identical sets share one engine) and tags
        every later match RPC with the returned set id."""
        self._expected_cfg = (list(patterns), bool(ignore_case),
                              list(exclude or []))
        info = await self.hello()
        if check_server_config(self._target, info, patterns, ignore_case,
                               exclude) == "register":
            await self._register_set()

    async def ensure_registered(self, patterns: list[str],
                                ignore_case: bool = False,
                                exclude: "list[str] | None" = None
                                ) -> None:
        """Record the expected config and register it (idempotent —
        re-registration of a live set is a content-addressed no-op).
        The sharded tier calls this per endpoint after its own
        fleet-wide Hello sweep."""
        self._expected_cfg = (list(patterns), bool(ignore_case),
                              list(exclude or []))
        await self._register_set()

    async def _register_set(self) -> None:
        assert self._expected_cfg is not None
        pats, ic, excl = self._expected_cfg
        resp = transport.decode_register_response(await self._call(
            self._register_rpc,
            transport.encode_register_request(
                pats, excl, ic, weight=tenant_weight()),
            "rpc.register"))
        self._set_id = resp["set"]

    async def _call_set(self, rpc, build, fault_point: str):
        """One match RPC carrying the tenant set id, transparently
        re-registering ONCE when the server evicted the set while it
        was cold (the eviction/re-register roundtrip is part of the
        registry contract, not an error the collector should see)."""
        try:
            return await self._call(rpc, build(self._set_id), fault_point)
        except SetEvicted:
            if self._expected_cfg is None:
                raise
            await self._register_set()
            try:
                return await self._call(rpc, build(self._set_id),
                                        fault_point)
            except SetEvicted as e:
                # Evicted AGAIN before the retry landed: the registry
                # is in capacity churn (more active tenants than
                # KLOGS_TENANT_MAX_SETS). That is an overload
                # condition, not a config bug — degrade/fail over like
                # any other unavailability instead of killing the run.
                raise Unavailable(
                    f"filter service at {self._target}: set evicted "
                    f"again immediately after re-registration "
                    f"(registry capacity churn): {e}") from e

    async def match(self, lines: list[bytes]) -> list[bool]:
        resp = await self._call_set(
            self._match_rpc,
            lambda sid: transport.encode_match_request(lines, set_id=sid),
            "rpc.match")
        return transport.decode_match_response(resp)

    async def match_framed(self, payload: bytes, offsets):
        """Framed-batch match: O(1) per-batch wire cost both ways (see
        transport.py). Returns a numpy bool array. Falls back to the
        legacy Match against a server that predates the framed
        protocol (Hello without "framed")."""
        if self._server_framed is None:
            await self.hello()
        if not self._server_framed:
            import numpy as np

            from klogs_tpu.filters.base import split_frame

            return np.asarray(
                await self.match(split_frame(payload, offsets)), dtype=bool)
        resp = await self._call_set(
            self._match_framed_rpc,
            lambda sid: transport.encode_framed_request(payload, offsets,
                                                        set_id=sid),
            "rpc.match")
        return transport.decode_framed_response(resp)

    async def aclose(self) -> None:
        """Graceful shutdown: awaited from the pipeline so the channel
        closes before the event loop exits (a fire-and-forget task here
        leaks and warns under an exiting loop)."""
        pending, self._close_task = self._close_task, None
        if pending is not None:
            # A prior sync close() parked its work here; settle it so
            # the task can't outlive the client (and double-closing the
            # channel below stays a no-op).
            try:
                await pending
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        await self._channel.close()

    def close(self) -> None:
        # Sync fallback (non-async teardown paths only): schedule if a
        # loop is running, else the channel dies with the process.
        import asyncio

        try:
            loop = asyncio.get_running_loop()
            # Stored on self so the close isn't an untracked
            # fire-and-forget task (task-lifecycle invariant) and a
            # caller that DOES have a loop can await/inspect it.
            self._close_task = loop.create_task(self._channel.close())
        except RuntimeError:
            pass
