"""`python -m klogs_tpu.service` — run the filter service daemon."""

import argparse
import asyncio
import sys

from klogs_tpu.filters.compiler.parser import RegexSyntaxError
from klogs_tpu.service.server import serve


def main() -> None:
    ap = argparse.ArgumentParser(
        prog="klogs-filterd",
        description="klogs_tpu filter service: owns the TPU engine, "
        "serves Match RPCs to log collectors",
    )
    ap.add_argument("--match", action="append", required=True,
                    help="regex pattern (repeatable)")
    ap.add_argument("--backend", choices=["cpu", "tpu"], default="tpu")
    ap.add_argument("-I", "--ignore-case", action="store_true",
                    dest="ignore_case",
                    help="case-insensitive patterns (collectors must "
                    "connect with matching -I or the pattern handshake "
                    "rejects them)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=50051)
    ns = ap.parse_args()
    try:
        asyncio.run(serve(ns.match, ns.backend, ns.host, ns.port,
                          ignore_case=ns.ignore_case))
    except KeyboardInterrupt:
        pass
    except RegexSyntaxError as e:
        print(f"unsupported --match pattern: {e}", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
