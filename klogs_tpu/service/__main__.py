"""`python -m klogs_tpu.service` — run the filter service daemon."""

import argparse
import asyncio
import sys

from klogs_tpu.filters.compiler.parser import RegexSyntaxError
from klogs_tpu.service.server import serve


def main() -> None:
    ap = argparse.ArgumentParser(
        prog="klogs-filterd",
        description="klogs_tpu filter service: owns the TPU engine, "
        "serves Match RPCs to log collectors",
    )
    ap.add_argument("--match", action="append", default=[],
                    help="regex pattern to KEEP (repeatable)")
    ap.add_argument("--exclude", action="append", default=[],
                    help="regex pattern to DROP even when kept "
                    "(repeatable; alone = keep all non-matching)")
    ap.add_argument("--backend", choices=["cpu", "tpu"], default="tpu")
    ap.add_argument("--multi-set", action="store_true", dest="multi_set",
                    help="multi-tenant registry mode: collectors "
                    "register their own pattern sets (content-addressed "
                    "— identical sets share one compiled engine) and "
                    "are admitted weighted-fair with per-set quotas; "
                    "--match/--exclude become the optional default set "
                    "for legacy collectors (docs/TENANCY.md; "
                    "KLOGS_TENANT_* env knobs)")
    ap.add_argument("-I", "--ignore-case", action="store_true",
                    dest="ignore_case",
                    help="case-insensitive patterns (collectors must "
                    "connect with matching -I or the pattern handshake "
                    "rejects them)")
    ap.add_argument("--host", default="127.0.0.1",
                    help='bind address; "unix:/path.sock" serves a Unix '
                    "domain socket (co-located collector deployments)")
    ap.add_argument("--port", type=int, default=50051)
    ap.add_argument("--tls-cert", help="PEM server certificate (enables TLS)")
    ap.add_argument("--tls-key", help="PEM server private key")
    ap.add_argument("--tls-client-ca",
                    help="PEM CA bundle; require+verify client certs (mTLS)")
    ap.add_argument("--auth-token-file",
                    help="file with a shared bearer token (e.g. a mounted "
                    "Kubernetes Secret); RPCs without it are rejected")
    ap.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve Prometheus /metrics plus /healthz "
                    "(liveness) and /readyz (readiness; unready until "
                    "the warmup batch clears the cold-start compile) on "
                    "this HTTP port (0 = ephemeral; binds 127.0.0.1)")
    ap.add_argument("--trace-json", default=None, dest="trace_json",
                    metavar="PATH",
                    help="write every finished trace span as one JSON "
                    "line to PATH (server-side batch tracing; continues "
                    "a collector's trace when its RPC carries the "
                    "traceparent metadata). Implies KLOGS_TRACE_SAMPLE=1 "
                    "unless that variable is set")
    ap.add_argument("--profile-json", default=None, dest="profile_json",
                    metavar="PATH",
                    help="append one JSON line per profiler tick to "
                    "PATH: per-stage busy-seconds/utilization, queue/"
                    "in-flight samples, and the offered/admitted/"
                    "headroom capacity block. Enables the continuous "
                    "pipeline profiler (KLOGS_PROFILE_SAMPLE pins the "
                    "span-sampling rate; 0 disables). The same "
                    "snapshot serves /profile on --metrics-port "
                    "(docs/OBSERVABILITY.md)")
    ap.add_argument("--metrics-host", default="127.0.0.1",
                    metavar="HOST",
                    help="metrics/health bind address. Cross-node "
                    "sharded collectors drain this server via its "
                    "advertised /readyz, which they can only reach when "
                    "this binds a routable address (e.g. 0.0.0.0); the "
                    "loopback default keeps the sidecar private and "
                    "collectors then rely on breakers alone")
    ns = ap.parse_args()
    if ns.auth_token_file:
        # Fail fast on a bad path/empty file; the server re-reads the
        # file per RPC afterwards so Secret rotation needs no restart.
        try:
            with open(ns.auth_token_file) as f:
                if not f.read().strip():
                    ap.error(f"--auth-token-file {ns.auth_token_file} is empty")
        except OSError as e:
            ap.error(f"cannot read --auth-token-file: {e}")
    try:
        asyncio.run(serve(ns.match, ns.backend, ns.host, ns.port,
                          ignore_case=ns.ignore_case,
                          multi_set=ns.multi_set,
                          tls_cert=ns.tls_cert, tls_key=ns.tls_key,
                          tls_client_ca=ns.tls_client_ca,
                          auth_token_file=ns.auth_token_file,
                          exclude=ns.exclude,
                          metrics_port=ns.metrics_port,
                          metrics_host=ns.metrics_host,
                          trace_json=ns.trace_json,
                          profile_json=ns.profile_json))
    except KeyboardInterrupt:
        pass
    except RegexSyntaxError as e:  # subclasses ValueError: catch first
        print(f"unsupported --match pattern: {e}", file=sys.stderr)
        raise SystemExit(1)
    except ValueError as e:
        # FilterServer validates TLS pairing (cert+key, client-ca needs
        # both) — surface as the friendly one-liner.
        print(f"klogs-filterd: {e}", file=sys.stderr)
        raise SystemExit(1)


if __name__ == "__main__":
    main()
