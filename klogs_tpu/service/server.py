"""Filter service server: owns the engine + device, serves Match RPCs.

Run standalone:  python -m klogs_tpu.service --match ERROR --match 'WARN.*' \
                     --backend tpu --port 50051

All client batches funnel into one AsyncFilterService, so concurrent
collectors coalesce into shared device batches (the device's efficient
regime) regardless of how small each client's flushes are.
"""

import asyncio

import grpc

from klogs_tpu.filters.async_service import AsyncFilterService
from klogs_tpu.service import transport
from klogs_tpu.version import BUILD_VERSION


def _make_filter(patterns: list[str], backend: str,
                 ignore_case: bool = False):
    if backend == "cpu":
        from klogs_tpu.filters.cpu import RegexFilter

        return RegexFilter(patterns, ignore_case=ignore_case)
    from klogs_tpu.filters.tpu import NFAEngineFilter

    return NFAEngineFilter(patterns, ignore_case=ignore_case)


class FilterServer:
    def __init__(self, patterns: list[str], backend: str = "tpu",
                 host: str = "127.0.0.1", port: int = 50051,
                 ignore_case: bool = False):
        self.patterns = list(patterns)
        self.backend = backend
        self.host = host
        self.port = port
        self.ignore_case = ignore_case
        self._service = AsyncFilterService(
            _make_filter(patterns, backend, ignore_case=ignore_case))
        self._server: grpc.aio.Server | None = None

    async def _hello(self, request: bytes, context) -> bytes:
        return transport.pack({
            "patterns": self.patterns,
            "ignore_case": self.ignore_case,
            "backend": self.backend,
            "version": BUILD_VERSION,
        })

    async def _match(self, request: bytes, context) -> bytes:
        lines = transport.decode_match_request(request)
        mask = await self._service.match(lines)
        return transport.encode_match_response(mask)

    async def start(self) -> int:
        """Binds and starts serving; returns the bound port (useful when
        port=0 asks the OS for an ephemeral one)."""
        handler = grpc.method_handlers_generic_handler(
            transport.SERVICE,
            {
                "Hello": grpc.unary_unary_rpc_method_handler(self._hello),
                "Match": grpc.unary_unary_rpc_method_handler(self._match),
            },
        )
        # Jumbo batches (thousands of long lines) exceed gRPC's 4 MB
        # default message cap; the batcher bounds real sizes well under
        # this.
        self._server = grpc.aio.server(options=[
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ])
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        return self.port

    async def wait(self) -> None:
        await self._server.wait_for_termination()

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
        self._service.close()


async def serve(patterns: list[str], backend: str, host: str, port: int,
                ignore_case: bool = False) -> None:
    server = FilterServer(patterns, backend, host=host, port=port,
                       ignore_case=ignore_case)
    bound = await server.start()
    print(f"klogs filterd: serving {len(patterns)} pattern(s) "
          f"[{backend}] on {host}:{bound}", flush=True)
    try:
        await server.wait()
    finally:
        await server.stop()
