"""Filter service server: owns the engine + device, serves Match RPCs.

Run standalone:  python -m klogs_tpu.service --match ERROR --match 'WARN.*' \
                     --backend tpu --port 50051

All client batches funnel into one AsyncFilterService, so concurrent
collectors coalesce into shared device batches (the device's efficient
regime) regardless of how small each client's flushes are.

Transport security (for the collector-in-cluster -> filterd-near-TPU
deployment, where the hop crosses node boundaries):

- TLS: ``tls_cert``/``tls_key`` serve over TLS; adding
  ``tls_client_ca`` requires and verifies client certificates (mTLS).
- Bearer auth: ``auth_token`` (or ``auth_token_file``, re-read per RPC
  so a rotated mounted Secret keeps working without a restart) rejects
  any RPC not carrying ``authorization: Bearer <token>`` metadata with
  UNAUTHENTICATED — the cert-free option a Kubernetes Secret deploys in
  one line. Token-only mode over plaintext sends the secret in the
  clear; combine with TLS on untrusted networks (the server prints a
  reminder).

Both default off: the localhost/co-located case stays zero-config.
Partial TLS configuration (cert without key, client-ca without cert) is
a constructor error, never a silent plaintext fallback.
"""

import asyncio
import hmac
import time

import grpc

from klogs_tpu.filters.async_service import AsyncFilterService
from klogs_tpu.obs import trace
from klogs_tpu.obs.profiler import PROFILER, FleetCapacity
from klogs_tpu.service import transport
from klogs_tpu.version import BUILD_VERSION


def _make_filter(patterns: list[str], backend: str,
                 ignore_case: bool = False,
                 exclude: "list[str] | None" = None,
                 stats=None):
    from klogs_tpu.filters.base import build_include_exclude

    made = []

    def one(pats):
        if backend == "cpu":
            from klogs_tpu.filters.cpu import best_host_filter

            # Index metrics ride the first-built side's registry, same
            # rule as the stats wiring below.
            f = best_host_filter(
                pats, ignore_case=ignore_case,
                registry=stats.registry
                if stats is not None and not made else None)[0]
        else:
            from klogs_tpu.filters.tpu import NFAEngineFilter

            # Stats ride the first-built side only (≙ make_pipeline's
            # rule: feeding both combiner inputs would double-count).
            f = NFAEngineFilter(pats, ignore_case=ignore_case,
                                stats=stats if not made else None)
        made.append(f)
        return f

    return build_include_exclude(one, patterns, exclude)


def _uses_device_sweep(filt) -> bool:
    """True when any engine behind ``filt`` (possibly an
    include/exclude combiner) runs the fused device literal sweep —
    the TPU engine's sweep tables or an IndexedFilter narrowing on the
    device path."""
    stack = [filt]
    while stack:
        f = stack.pop()
        for attr in ("include", "exclude", "inner"):
            sub = getattr(f, attr, None)
            if sub is not None:
                stack.append(sub)
        if getattr(f, "_sweep_tables", None) is not None and \
                getattr(f, "_sweep_path", "device") == "device" and \
                not getattr(f, "bypassed", False):
            # bypassed: an IndexedFilter that switched itself to
            # scan-all no longer sweeps at all — stop advertising it.
            return True
        # A mesh-backed engine carries the sweep inside MeshEngine
        # (its _fn_sweep, surfaced as `swept`), not in the wrapper's
        # _sweep_tables.
        if getattr(getattr(f, "_engine", None), "swept", False):
            return True
    return False


def _read_tls(path: str, what: str) -> bytes:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError as e:
        # ValueError: __main__'s friendly one-liner path.
        raise ValueError(f"cannot read {what} {path}: {e}") from e


def _client_host(peer: str) -> str:
    """gRPC peer -> bounded-cardinality client label: the HOST only.
    Ports churn per connection ('ipv4:127.0.0.1:54321'), so keeping
    them would mint a new series per reconnect."""
    if peer.startswith(("ipv4:", "ipv6:")):
        return peer.split(":", 1)[1].rsplit(":", 1)[0]
    return peer or "unknown"


class FilterServer:
    def __init__(self, patterns: list[str], backend: str = "tpu",
                 host: str = "127.0.0.1", port: int = 50051,
                 ignore_case: bool = False,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_client_ca: str | None = None,
                 auth_token: str | None = None,
                 auth_token_file: str | None = None,
                 exclude: "list[str] | None" = None,
                 metrics_port: int | None = None,
                 metrics_host: str = "127.0.0.1",
                 registry=None,
                 multi_set: bool = False,
                 tenant_max_sets: "int | None" = None,
                 tenant_quota_lines: "int | None" = None,
                 tenant_idle_s: "float | None" = None):
        if bool(tls_cert) != bool(tls_key):
            raise ValueError(
                "tls_cert and tls_key must be provided together "
                "(refusing to fall back to plaintext on partial TLS config)")
        if tls_client_ca and not tls_cert:
            raise ValueError("tls_client_ca (mTLS) requires tls_cert/tls_key")
        if auth_token and auth_token_file:
            raise ValueError("pass auth_token OR auth_token_file, not both")
        self.patterns = list(patterns)
        self.exclude = list(exclude or [])
        self.multi_set = multi_set
        if not self.patterns and not self.exclude and not multi_set:
            raise ValueError("need at least one --match or --exclude pattern")
        self.backend = backend
        self.host = host
        self.port = port
        self.ignore_case = ignore_case
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self.tls_client_ca = tls_client_ca
        self.auth_token = auth_token
        self.auth_token_file = auth_token_file
        # Observability sidecar (opt-in, --metrics-port): the registry
        # backs FilterStats AND the engine/coalescer/RPC families, so
        # /metrics is one consistent panel over the live pipeline.
        # Without it the server runs the zero-instrumentation path.
        self.metrics_port = metrics_port
        self.metrics_host = metrics_host
        self.registry = None
        self.health = None
        self._stats = None
        self._http = None
        self._warmup_task: asyncio.Task | None = None
        self._m_rpc = None
        if metrics_port is not None:
            from klogs_tpu import obs
            from klogs_tpu.filters.base import FilterStats

            # Per-SERVER registry by default: a restarted in-process
            # filterd must not inherit the previous instance's
            # counters into its /metrics.
            self.registry = registry if registry is not None else obs.Registry()
            # The whole inventory up front: a scrape during cold start
            # already shows every layer's (zero-valued) families.
            obs.register_all(self.registry)
            self.registry.family("klogs_build_info").labels(
                version=BUILD_VERSION).set(1)
            # Trace/flight-recorder counters scrape from this server's
            # sidecar (the tracer itself is process-global — one trace
            # story per process; a later server instance rebinds).
            from klogs_tpu.obs import trace as _trace

            _trace.TRACER.bind_registry(self.registry)
            _trace.RECORDER.bind_registry(self.registry)
            PROFILER.bind_registry(self.registry)
            self._stats = FilterStats(registry=self.registry)
            self._m_rpc = {
                "req": self.registry.family("klogs_rpc_requests_total"),
                "err": self.registry.family("klogs_rpc_errors_total"),
                "lat": self.registry.family("klogs_rpc_request_seconds"),
                "client": self.registry.family(
                    "klogs_rpc_client_requests_total"),
            }
            self.health = obs.Health()
            # Liveness: the coalescer loop must still accept work —
            # a closed service means restart; a merely-cold one does not.
            self.health.add_live_check(
                "coalescer", lambda: self._service is None
                or not self._service._closed)
        # Fleet capacity accounting (offered vs admitted lines +
        # headroom), advertised through Hello whether or not the
        # metrics sidecar runs — the sharded client re-exports it
        # per endpoint for the HPA scrape. The profiler carries it on
        # /profile too (a later server instance rebinds, like the
        # tracer's registry binding above).
        self.capacity = FleetCapacity(registry=self.registry)
        PROFILER.attach_capacity(self.capacity)
        # Multi-tenant registry (docs/TENANCY.md): content-addressed
        # pattern sets behind weighted-fair admission; the startup set
        # (when present) is adopted as a pinned default lane so legacy
        # un-tagged RPCs compete fairly with registered tenants.
        self.tenants = None
        self.default_set: "str | None" = None
        self._sweep_task: asyncio.Task | None = None
        self._sweep_stop: "asyncio.Event | None" = None
        if multi_set:
            from klogs_tpu.service.tenancy import PatternSetRegistry

            def factory(pats: list[str], excl: list[str],
                        ic: bool):
                # Tenant engines share the server's FilterStats (and
                # registry): engine metrics, sweep-fallback counters,
                # and flight-recorder triggers must fire for REGISTERED
                # sets too, not just the startup default — per-set
                # attribution rides the klogs_tenant_* families.
                return _make_filter(pats, self.backend, ignore_case=ic,
                                    exclude=excl, stats=self._stats)

            self.tenants = PatternSetRegistry(
                factory, stats=self._stats,
                max_sets=tenant_max_sets,
                quota_lines=tenant_quota_lines,
                idle_evict_s=tenant_idle_s)
        # The startup set compiles exactly as before (single-set path
        # byte-identical); a registry-only multi-set server (no --match)
        # has no default engine until the first Register RPC. In
        # registry mode the default service rides the registry's SHARED
        # fetch pool + in-flight budget — the process owns one device,
        # and legacy un-tagged traffic must not double that budget.
        self._filter = None
        self._service = None
        if self.patterns or self.exclude:
            self._filter = _make_filter(patterns, backend,
                                        ignore_case=ignore_case,
                                        exclude=self.exclude,
                                        stats=self._stats)
            shared = ({} if self.tenants is None
                      else dict(executor=self.tenants.executor,
                                in_flight=self.tenants.in_flight))
            self._service = AsyncFilterService(self._filter,
                                               stats=self._stats,
                                               **shared)
            if self.tenants is not None:
                self.default_set = self.tenants.adopt(
                    self.patterns, self.exclude, self.ignore_case,
                    self._service)
        self._server: grpc.aio.Server | None = None

    @property
    def device_sweep(self) -> bool:
        """Engine-detail discovery (Hello): whether the thousand-
        pattern device sweep is gating this server's kernel RIGHT NOW
        — an operator debugging a fleet throughput step needs to see
        which servers run the fused path without scraping each
        sidecar. Computed per Hello, not cached at startup: a sweep
        that degraded mid-run (kernel failure, host fallback) must
        stop being advertised. In registry mode ANY registered set's
        engine counts — a registry-only server whose tenants run the
        fused path must not advertise False."""
        if self._filter is not None and _uses_device_sweep(self._filter):
            return True
        if self.tenants is not None:
            return any(
                not e.pinned and _uses_device_sweep(e.service._filter)
                for e in self.tenants.entries())
        return False

    @property
    def auth_enabled(self) -> bool:
        return bool(self.auth_token or self.auth_token_file)

    def _current_token(self) -> str | None:
        if self.auth_token_file:
            # Re-read per check: a rotated mounted Secret (kubelet
            # updates the file) keeps authenticating without a restart
            # — same rationale as kubeconfig.in_cluster_creds.
            try:
                with open(self.auth_token_file) as f:
                    return f.read().strip() or None
            except OSError:
                return None
        return self.auth_token

    async def _check_auth(self, context) -> bool:
        if not self.auth_enabled:
            return True
        # The token-file re-read is disk I/O on a per-RPC path: off the
        # event loop, or one slow/NFS-mounted Secret volume stalls every
        # concurrent collector's RPCs behind it.
        if self.auth_token_file:
            token = await asyncio.to_thread(self._current_token)
        else:
            token = self.auth_token
        meta = dict(context.invocation_metadata() or ())
        got = meta.get("authorization", "")
        # Compare utf-8 bytes: compare_digest on str raises TypeError
        # for non-ASCII, which would turn every RPC into UNKNOWN.
        if token and hmac.compare_digest(
                got.encode(), f"Bearer {token}".encode()):
            return True
        await context.abort(grpc.StatusCode.UNAUTHENTICATED,
                            "missing or wrong bearer token")
        return False  # unreachable; abort raises

    def _instrumented(self, method: str, handler):
        """RPC-layer metrics wrapper: requests/errors/latency by
        method, plus per-client-host counts. Identity when metrics are
        off (no per-RPC overhead)."""
        if self._m_rpc is None:
            return handler
        from klogs_tpu.obs.trace import TRACER

        m = self._m_rpc
        req = m["req"].labels(method=method)
        err = m["err"].labels(method=method)
        lat = m["lat"].labels(method=method)

        async def wrapped(request: bytes, context) -> bytes:
            t0 = time.perf_counter()
            req.inc()
            m["client"].labels(
                client=_client_host(context.peer() or "")).inc()
            try:
                return await handler(request, context)
            except BaseException:
                # Aborts (UNAUTHENTICATED / INVALID_ARGUMENT) raise
                # through here too — they ARE failed RPCs.
                err.inc()
                raise
            finally:
                # Exemplar: the rpc.server span (still open — _traced
                # wraps outside this layer) links the latency sample to
                # its trace in the exposition.
                lat.observe(time.perf_counter() - t0,
                            exemplar=TRACER.exemplar())

        return wrapped

    def _traced(self, method: str, handler):
        """Tracing wrapper (outermost): continue the collector's batch
        trace across the wire — the traceparent metadata entry parents
        this RPC's ``rpc.server`` span under the client's ``rpc.client``
        span, so one trace covers collector sink -> shard routing ->
        RPC -> server coalescer -> device. Without the metadata (old
        client, tracing off) the RPC roots its own trace under local
        sampling; when neither side records, the handler runs bare."""
        from klogs_tpu.obs.trace import TRACER

        async def wrapped(request: bytes, context) -> bytes:
            ctx = transport.extract_trace(context.invocation_metadata())
            if ctx is None and not TRACER.enabled:
                return await handler(request, context)
            with TRACER.span("rpc.server", parent=ctx, method=method,
                             request_bytes=len(request)):
                return await handler(request, context)

        return wrapped

    async def _warmup(self) -> None:
        """Cold-start gate behind /readyz: push one real (tiny) framed
        batch through the coalescer and engine. Success proves the
        engine compiled, the device answered, and the coalescer loop
        runs — the three things 'ready' means here. Until then the
        server is live but NOT ready (routing traffic to a compiling
        filterd queues RPCs behind a multi-second jit trace)."""
        from klogs_tpu.filters.base import frame_lines

        try:
            if self._service is None:
                # Registry-only multi-set server: nothing compiles until
                # the first Register RPC, so the server is ready as soon
                # as it binds (each registration pays its own compile
                # off the event loop).
                self.health.mark_warm()
                return
            payload, offsets, _ = frame_lines([b"klogs-warmup probe"])
            await self._service.match_framed(payload, offsets)
            # mark_warm, not set_ready: a drain that raced the warmup
            # (rolling restart right after start) must stick.
            self.health.mark_warm()
        except Exception as e:
            print(f"klogs filterd: warmup batch failed ({e}); "
                  "/readyz stays unready", flush=True)

    def _capacity_keys(self) -> dict:
        """The fleet-capacity advertisement every Hello carries, next
        to metrics_port/device_sweep: the sharded client re-exports
        these per endpoint (klogs_fleet_endpoint_*) and may weigh
        routing by headroom later. Old clients ignore the keys."""
        cap = self.capacity.doc()
        return {
            "headroom": cap["headroom"],
            "fleet_offered_lines": cap["offered_lines"],
            "fleet_admitted_lines": cap["admitted_lines"],
        }

    async def _hello(self, request: bytes, context) -> bytes:
        await self._check_auth(context)
        if self.tenants is not None:
            return await self._hello_multi(request)
        return transport.pack({
            **self._capacity_keys(),
            "patterns": self.patterns,
            "exclude": self.exclude,
            "ignore_case": self.ignore_case,
            "backend": self.backend,
            "version": BUILD_VERSION,
            "framed": True,
            # Readiness discovery for the sharded client tier: where
            # /readyz lives (bound sidecar host+port), so a collector
            # can drain this server on rolling restarts without extra
            # configuration. port=None when the sidecar is off — the
            # client then relies on breakers alone. The host matters:
            # a loopback-bound sidecar is unreachable from a remote
            # collector, and the client must know NOT to probe it (a
            # refused probe would wrongly demote a healthy server).
            # Old clients ignore both keys.
            "metrics_port": self.metrics_port,
            "metrics_host": self.metrics_host,
            # Engine detail: whether the fused device literal sweep is
            # gating this server's kernel (thousand-pattern mode).
            # Old clients ignore the key.
            "device_sweep": self.device_sweep,
        })

    async def _hello_multi(self, request: bytes) -> bytes:
        """Multi-set Hello: answer verify_patterns against the REGISTRY
        (match-by-fingerprint), not the single startup list — a second
        collector with a different set registers instead of hard-failing
        PatternMismatch. A request carrying the collector's invocation
        is echoed back when that fingerprint is registered (so the
        legacy client-side comparison passes); the legacy empty Hello
        gets the default (startup) set, keeping old collectors working
        against a multi-set server unchanged."""
        from klogs_tpu.service.shard import pattern_fingerprint

        want = transport.decode_hello_request(request)
        patterns, exclude, ignore_case = (self.patterns, self.exclude,
                                          self.ignore_case)
        set_id: "str | None" = self.default_set
        registered = self.default_set is not None
        if want is not None:
            set_id = pattern_fingerprint(want["patterns"], want["exclude"],
                                         want["ignore_case"])
            entry = self.tenants.get(set_id)
            registered = entry is not None
            if registered:
                patterns = entry.patterns
                exclude = entry.exclude
                ignore_case = entry.ignore_case
        sp = trace.TRACER.current_span()
        if sp is not None and set_id is not None:
            sp.set_attr("tenant", set_id)
        return transport.pack({
            **self._capacity_keys(),
            "patterns": patterns,
            "exclude": exclude,
            "ignore_case": ignore_case,
            "backend": self.backend,
            "version": BUILD_VERSION,
            "framed": True,
            "metrics_port": self.metrics_port,
            "metrics_host": self.metrics_host,
            "device_sweep": self.device_sweep,
            # Registry mode: the client should Register its set (once)
            # and tag match RPCs with the returned id. "sets" is the
            # live registered count (banner/fleet debugging).
            "multi_set": True,
            "sets": self.tenants.count,
            "set": set_id,
            "registered": registered,
        })

    async def _register(self, request: bytes, context) -> bytes:
        """Register-once RPC: content-addressed, so two tenants with
        identical sets share one compiled engine (the engine-build
        counter must NOT advance on the second registration)."""
        await self._check_auth(context)
        if self.tenants is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "this filterd runs in single-set mode (start it with "
                "--multi-set to accept registrations)")
        try:
            req = transport.decode_register_request(request)
        except (ValueError, KeyError, TypeError) as e:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"bad register request: {e}")
        try:
            set_id, shared = await self.tenants.register(
                req["patterns"], req["exclude"], req["ignore_case"],
                weight=req["weight"])
        except ValueError as e:
            # RegexSyntaxError and friends: the tenant's OWN set is
            # broken — its registration fails, nobody else's.
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"cannot compile pattern set: {e}")
        sp = trace.TRACER.current_span()
        if sp is not None:
            sp.set_attr("tenant", set_id)
        return transport.encode_register_response(
            set_id, shared, self.tenants.count)

    def _route_set(self, set_id: "str | None") -> "str | None":
        """Which registry lane serves this request: its explicit set
        tag, else the default (startup) set."""
        return set_id if set_id is not None else self.default_set

    async def _tenant_match(self, set_id: "str | None", context, run):
        """Route one match RPC through the registry: admission, quota
        shed (RESOURCE_EXHAUSTED — the client degrades it through the
        existing --on-filter-error path), unknown/evicted set
        (FAILED_PRECONDITION — the client re-registers and retries)."""
        from klogs_tpu.service.tenancy import OverQuota, SetNotRegistered

        lane = self._route_set(set_id)
        if lane is None:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"{transport.SET_NOT_REGISTERED}: this multi-set "
                "filterd has no default pattern set; register one "
                "first")
        sp = trace.TRACER.current_span()
        if sp is not None:
            sp.set_attr("tenant", lane)
        try:
            return await run(lane)
        except OverQuota as e:
            await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                f"{transport.OVER_QUOTA}: {e}")
        except SetNotRegistered as e:
            await context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"{transport.SET_NOT_REGISTERED}: {e}")

    async def _match(self, request: bytes, context) -> bytes:
        await self._check_auth(context)
        try:
            lines, set_id = transport.decode_match_request(request)
        except (ValueError, KeyError, TypeError) as e:
            # Same contract as _match_framed: a malformed request fails
            # ITS OWN RPC with a clean status, never an UNKNOWN
            # traceback.
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"bad match request: {e}")
        # Capacity accounting: offered BEFORE admission, admitted only
        # when verdicts came back — an admission shed (OverQuota abort)
        # leaves the gap the autoscaling signal measures.
        self.capacity.note_offered(len(lines))
        if self.tenants is not None:
            mask = await self._tenant_match(
                set_id, context,
                lambda lane: self.tenants.match(lane, lines))
        else:
            mask = await self._service.match(lines)
        self.capacity.note_admitted(len(lines))
        return transport.encode_match_response(mask)

    async def _match_framed(self, request: bytes, context) -> bytes:
        """Framed hot path: payload+offsets in, raw mask bytes out —
        no per-line Python object anywhere server-side (the batch goes
        contiguous buffer -> C pack_classify_framed -> device -> numpy
        mask)."""
        await self._check_auth(context)
        try:
            payload, offsets, set_id = transport.decode_framed_request(
                request)
        except (ValueError, KeyError, TypeError) as e:
            # Malformed framing fails ITS OWN RPC with a clean status —
            # decode validation guarantees it can never reach the
            # coalescer shared with other collectors.
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                f"bad framed request: {e}")
        # Same offered/admitted discipline as _match (framed hot path:
        # two integer adds per BATCH, nothing per line).
        n_lines = max(len(offsets) - 1, 0)
        self.capacity.note_offered(n_lines)
        if self.tenants is not None:
            mask = await self._tenant_match(
                set_id, context,
                lambda lane: self.tenants.match_framed(
                    lane, payload, offsets))
        else:
            mask = await self._service.match_framed(payload, offsets)
        self.capacity.note_admitted(n_lines)
        return transport.encode_framed_response(mask)

    async def start(self) -> int:
        """Binds and starts serving; returns the bound port (useful when
        port=0 asks the OS for an ephemeral one)."""
        handler = grpc.method_handlers_generic_handler(
            transport.SERVICE,
            {
                "Hello": grpc.unary_unary_rpc_method_handler(
                    self._traced("Hello", self._instrumented(
                        "Hello", self._hello))),
                "Match": grpc.unary_unary_rpc_method_handler(
                    self._traced("Match", self._instrumented(
                        "Match", self._match))),
                "MatchFramed": grpc.unary_unary_rpc_method_handler(
                    self._traced("MatchFramed", self._instrumented(
                        "MatchFramed", self._match_framed))),
                "Register": grpc.unary_unary_rpc_method_handler(
                    self._traced("Register", self._instrumented(
                        "Register", self._register))),
            },
        )
        # Jumbo batches (thousands of long lines) exceed gRPC's 4 MB
        # default message cap; the batcher bounds real sizes well under
        # this.
        self._server = grpc.aio.server(options=[
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
        ])
        self._server.add_generic_rpc_handlers((handler,))
        # A host of the form "unix:/path.sock" binds a Unix domain
        # socket (grpc-native scheme) — the co-located collector->
        # filterd deployment on one TPU host skips the TCP stack
        # entirely; port is meaningless there.
        if self.host.startswith("unix:"):
            addr = self.host
        else:
            addr = f"{self.host}:{self.port}"
        if self.tls_cert and self.tls_key:
            # One-time reads, but start() runs on the loop (an in-process
            # collector may already be streaming): disk I/O goes through
            # a worker thread like every other blocking read here.
            key = await asyncio.to_thread(_read_tls, self.tls_key,
                                          "TLS key")
            cert = await asyncio.to_thread(_read_tls, self.tls_cert,
                                           "TLS certificate")
            ca = (await asyncio.to_thread(_read_tls, self.tls_client_ca,
                                          "client CA bundle")
                  if self.tls_client_ca else None)
            creds = grpc.ssl_server_credentials(
                [(key, cert)], root_certificates=ca,
                require_client_auth=ca is not None)
            self.port = self._server.add_secure_port(addr, creds)
        else:
            self.port = self._server.add_insecure_port(addr)
        await self._server.start()
        if self.metrics_port is not None:
            from klogs_tpu.obs import MetricsHTTPServer

            self._http = MetricsHTTPServer(
                self.registry, health=self.health,
                host=self.metrics_host, port=self.metrics_port)
            try:
                self.metrics_port = await self._http.start()
            except BaseException as e:
                # Unbindable metrics port — or a cancellation landing
                # mid-bind: tear the already-started gRPC server down
                # (serve()'s finally is not armed yet). OSError gets
                # the friendly ValueError path; everything else
                # (CancelledError included) re-raises after teardown.
                self._http = None
                await self._server.stop(0)
                if self.tenants is not None:
                    self.tenants.close()
                if self._service is not None:
                    self._service.close()
                if isinstance(e, OSError):
                    raise ValueError(
                        f"cannot bind metrics port "
                        f"{self.metrics_host}:{self.metrics_port}: {e}"
                    ) from e
                raise
            # Readiness flips when the warmup batch lands — NOT here:
            # /readyz during the cold-start compile must answer 503
            # while /healthz already answers 200.
            self._warmup_task = asyncio.get_running_loop().create_task(
                self._warmup())
        if self.tenants is not None and self.tenants.idle_evict_s > 0:
            # Cold-set reaper: idle compiled engines are released (and
            # re-registerable — the on-disk DFA LRU makes that a table
            # load, not a determinization).
            self._sweep_stop = asyncio.Event()
            self._sweep_task = asyncio.get_running_loop().create_task(
                self.tenants.run_idle_sweeper(self._sweep_stop))
        return self.port

    async def wait(self) -> None:
        await self._server.wait_for_termination()

    async def stop(self, grace: float = 1.0) -> None:
        if self._warmup_task is not None:
            self._warmup_task.cancel()
            try:
                await self._warmup_task
            except (asyncio.CancelledError, Exception):
                pass
            self._warmup_task = None
        if self._sweep_task is not None:
            if self._sweep_stop is not None:
                self._sweep_stop.set()
            self._sweep_task.cancel()
            try:
                await self._sweep_task
            except (asyncio.CancelledError, Exception):
                pass
            self._sweep_task = None
        if self._http is not None:
            await self._http.stop()
            self._http = None
        if self._server is not None:
            await self._server.stop(grace)
        if self.tenants is not None:
            # Registered sets drain and close; the pinned startup
            # service is the server's own, closed below.
            await self.tenants.aclose()
        if self._service is not None:
            self._service.close()


def banner_line(server: "FilterServer", where: str, mode: str) -> str:
    """The startup 'serving ...' line: registry mode reports the LIVE
    set count (the operating number — the fixed startup list, possibly
    empty, is just one lane), single-set mode stays byte-identical."""
    if server.tenants is not None:
        return (f"klogs filterd: serving pattern-set registry "
                f"({server.tenants.count} live set(s), cap "
                f"{server.tenants.max_sets}) [{server.backend}] on "
                f"{where} ({mode})")
    return (f"klogs filterd: serving {len(server.patterns)} pattern(s) "
            f"[{server.backend}] on {where} ({mode})")


async def serve(patterns: list[str], backend: str, host: str, port: int,
                ignore_case: bool = False,
                trace_json: "str | None" = None,
                profile_json: "str | None" = None,
                multi_set: bool = False, **security) -> None:
    if trace_json is not None:
        # Server-side batch tracing: spans root at rpc.server (or
        # continue a collector's trace via the metadata traceparent)
        # and land in this file as JSON lines; /traces on the metrics
        # sidecar serves the same spans.
        from klogs_tpu.obs import trace as _trace

        _trace.TRACER.enable_default()
        _trace.TRACER.set_json_path(trace_json)
    # Continuous utilization profiling: --profile-json turns it fully
    # on (unless KLOGS_PROFILE_SAMPLE pins a rate — including 0, the
    # kill switch); the env knob alone also enables it, feeding
    # /profile on the metrics sidecar without a file sink.
    PROFILER.maybe_enable()
    if profile_json is not None and PROFILER.enable():
        PROFILER.set_json_path(profile_json)
    server = FilterServer(patterns, backend, host=host, port=port,
                          ignore_case=ignore_case, multi_set=multi_set,
                          **security)
    bound = await server.start()
    prof_stop: "asyncio.Event | None" = None
    prof_task: "asyncio.Task | None" = None
    # Everything past start() runs under the stop() finally: a raise
    # while printing the banner (or starting the profiler ticker) must
    # not leak the bound listener or the ticker task.
    try:
        if PROFILER.enabled:
            prof_stop = asyncio.Event()
            prof_task = asyncio.get_running_loop().create_task(
                PROFILER.run_ticker(prof_stop))
        mode = "TLS" if server.tls_cert else "plaintext"
        if server.tls_client_ca:
            mode = "mTLS"
        if server.auth_enabled:
            mode += "+bearer"
            if not server.tls_cert:
                print("klogs filterd: WARNING bearer auth over plaintext "
                      "sends the token in the clear; add --tls-cert/"
                      "--tls-key on untrusted networks", flush=True)
        where = (server.host if server.host.startswith("unix:")
                 else f"{server.host}:{bound}")
        print(banner_line(server, where, mode), flush=True)
        if server.metrics_port is not None:
            print(f"klogs filterd: metrics on http://{server.metrics_host}:"
                  f"{server.metrics_port}/metrics (health: /healthz, "
                  "readiness: /readyz)", flush=True)
        await server.wait()
    finally:
        try:
            await server.stop()
        finally:
            # Nested so a cancellation landing inside server.stop()
            # still reaps the ticker instead of abandoning it.
            if prof_task is not None:
                # Final tick lands inside run_ticker before it
                # returns, so the JSONL stream ends with the complete
                # picture.
                if prof_stop is not None:
                    prof_stop.set()
                try:
                    await prof_task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
                PROFILER.set_json_path(None)
            # A degrade trigger armed near shutdown may have no
            # further local root span to ride — write it before the
            # process exits (mirrors the collector-side teardown in
            # app.py).
            from klogs_tpu.obs import trace as _trace2

            _trace2.RECORDER.flush()
