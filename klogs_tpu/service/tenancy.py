"""Multi-tenant pattern-set registry + weighted-fair admission.

One filterd process, many pattern sets (docs/TENANCY.md). The ROADMAP's
"millions of users" item: collectors no longer need a filterd deployed
per ``--match`` set — they *register* their set once (content-addressed
by ``pattern_fingerprint``, so two tenants invoked with the identical
set share ONE compiled engine and ONE coalescer, and their frames merge
into the same device batches) and then tag every match RPC with the
returned set id. The registry guards the shared device with three
mechanisms, in the order a batch meets them:

- **Quota shed** (``KLOGS_TENANT_QUOTA_LINES``): a lane whose pending
  lines (admitted + waiting) would exceed its quota has the batch shed
  *loudly* — the RPC fails RESOURCE_EXHAUSTED, the client raises
  ``Unavailable`` into the collector's existing ``--on-filter-error``
  degrade path, and ``klogs_tenant_shed_total{set}`` counts it. An
  abusive tenant's flood turns into ITS OWN degrade events, never a
  silent drop and never another tenant's latency.
- **Weighted-fair admission** (start-time fair queuing over
  ``KLOGS_TENANT_SLOTS`` concurrent admissions): each lane carries a
  virtual-time tag advanced by ``lines / weight`` per admitted batch;
  free slots go to the waiter with the lowest tag. A lane that floods
  only races ahead of its own tag — a quiet lane's next batch keeps a
  low tag and overtakes the flood at the next free slot, which is what
  bounds the well-behaved tenant's p99 while a sibling saturates.
- **Shared dispatch budget**: every set's ``AsyncFilterService`` runs
  over ONE fetch executor and ONE in-flight semaphore (the process owns
  one device), so per-set coalescing survives but total device
  occupancy is bounded globally, not per tenant.

Cold sets are evicted (idle past ``KLOGS_TENANT_IDLE_S``, or LRU past
``KLOGS_TENANT_MAX_SETS``): the compiled engine is released, while its
DFA tables stay in ``build_dfa_cached``'s on-disk LRU — so the next
registration of the same fingerprint is a table *load*, not a fresh
determinization. A match RPC naming an evicted set fails
FAILED_PRECONDITION and the client re-registers transparently.
"""

import asyncio
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from heapq import heappop, heappush
from typing import Any, Awaitable, Callable, Sequence

from klogs_tpu.filters.async_service import (
    DEFAULT_FETCH_WORKERS,
    DEFAULT_MAX_IN_FLIGHT,
    AsyncFilterService,
    _env_int,
)
from klogs_tpu.obs import trace
from klogs_tpu.resilience import Unavailable
from klogs_tpu.service.shard import pattern_fingerprint

# A set-building callable: (patterns, exclude, ignore_case) -> LogFilter.
# Injected (the server passes its _make_filter; tests pass a cheap host
# engine) so the registry never hard-depends on a backend.
FilterFactory = Callable[[list[str], list[str], bool], Any]

DEFAULT_MAX_SETS = 32
DEFAULT_QUOTA_LINES = 65536
DEFAULT_IDLE_EVICT_S = 900.0
DEFAULT_SLOTS = 32


# Positive-int knobs ride the coalescer's warn-and-fallback parser
# (_env_int, imported above); this float knob differs from the strict
# raising parser in filters/indexed.py on purpose — a bad KLOGS_TENANT
# value should degrade to the default loudly, not kill the server.
# (0 disables idle eviction.)
from klogs_tpu.utils.env import warn_nonneg_float as _env_float  # noqa: E402


class _BuildCancelled(Exception):
    """Internal single-flight marker: the BUILDER's task was cancelled
    mid-compile (its client hung up). Distinct from CancelledError so
    a rider awaiting the shared build can tell 'the builder died —
    rebuild' from 'I was cancelled myself — propagate'; the two are
    indistinguishable when both surface as CancelledError."""


class OverQuota(Unavailable):
    """A lane's pending lines would exceed its quota: the batch is shed.
    Subclasses Unavailable so the collector's --on-filter-error degrade
    routing (the *existing* shed path) catches it — a shed batch is a
    counted degrade event, never a silent drop."""


class SetNotRegistered(KeyError):
    """Match RPC named a fingerprint the registry does not hold (never
    registered, or evicted while cold). The server maps this to
    FAILED_PRECONDITION; clients re-register and retry once."""

    def __init__(self, set_id: str) -> None:
        super().__init__(set_id)
        self.set_id = set_id

    def __str__(self) -> str:
        return (f"set {self.set_id} not registered (register first; a "
                "cold set may have been evicted)")


class _Lane:
    """Per-set admission state: the fair-queue tag plus quota
    accounting. One lane per registry entry; the default (startup) set
    gets one too, so legacy un-tagged traffic competes fairly instead
    of bypassing admission."""

    __slots__ = ("set_id", "weight", "quota_lines", "pending_lines",
                 "tag", "m_shed", "m_pending", "m_lines")

    def __init__(self, set_id: str, weight: float, quota_lines: int,
                 registry: Any = None) -> None:
        self.set_id = set_id
        self.weight = max(weight, 1e-6)
        self.quota_lines = quota_lines
        # Lines admitted or waiting for admission (quota accounting).
        self.pending_lines = 0
        # Start-time-fair-queuing virtual time (see FairGate).
        self.tag = 0.0
        self.m_shed: Any = None
        self.m_pending: Any = None
        self.m_lines: Any = None
        if registry is not None:
            # Per-set series are bounded by KLOGS_TENANT_MAX_SETS (a
            # deployment knob), satisfying the label-cardinality rule.
            self.m_shed = registry.family(
                "klogs_tenant_shed_total").labels(set=set_id)
            self.m_pending = registry.family(
                "klogs_tenant_pending_lines").labels(set=set_id)
            self.m_lines = registry.family(
                "klogs_tenant_lines_total").labels(set=set_id)

    def note_pending(self, delta: int) -> None:
        self.pending_lines += delta
        if self.m_pending is not None:
            self.m_pending.set(self.pending_lines)


class _Slot:
    """One granted admission, as an async context manager so the grant
    is always released (span-discipline-style) even when the dispatch
    below fails."""

    __slots__ = ("_gate", "_lane", "_cost")

    def __init__(self, gate: "FairGate", lane: _Lane, cost: int) -> None:
        self._gate = gate
        self._lane = lane
        self._cost = cost

    async def __aenter__(self) -> "_Slot":
        await self._gate.acquire(self._lane, self._cost)
        return self

    async def __aexit__(self, *exc: object) -> None:
        self._gate.release()


class FairGate:
    """Start-time fair queuing over a fixed number of admission slots.

    Each lane carries a virtual-time ``tag``; a request stamps
    ``start = max(global_floor, lane.tag)`` and advances the lane's tag
    by ``cost / weight``. Free slots are granted to the waiter with the
    lowest start stamp, so a flooding lane's requests queue behind its
    own inflated tag while a quiet lane's next request — whose tag
    lagged at the floor — is admitted at the next release. Everything
    runs on the one event loop (the goroutine-discipline the resilience
    policy module documents); no locks."""

    def __init__(self, slots: int) -> None:
        self._free = slots
        # (start_tag, seq, future) — seq breaks ties FIFO.
        self._waiters: list[tuple[float, int, asyncio.Future]] = []
        self._seq = 0
        self._floor = 0.0

    @property
    def waiting(self) -> int:
        return len(self._waiters)

    def slot(self, lane: _Lane, cost: int) -> _Slot:
        return _Slot(self, lane, cost)

    async def acquire(self, lane: _Lane, cost: int) -> None:
        start = max(self._floor, lane.tag)
        lane.tag = start + float(max(cost, 1)) / lane.weight
        if self._free > 0 and not self._waiters:
            self._free -= 1
            self._floor = start
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._seq += 1
        heappush(self._waiters, (start, self._seq, fut))
        try:
            await fut
        except asyncio.CancelledError:
            # Granted-then-cancelled: the slot was already consumed on
            # our behalf — give it back or it leaks forever.
            if fut.done() and not fut.cancelled():
                self.release()
            raise

    def release(self) -> None:
        self._free += 1
        while self._waiters and self._free > 0:
            start, _, fut = heappop(self._waiters)
            if fut.done():  # cancelled while waiting
                continue
            self._free -= 1
            self._floor = start
            fut.set_result(None)


class SetEntry:
    """One registered pattern set: its compiled engine behind a per-set
    coalescer, plus the admission lane and eviction bookkeeping."""

    __slots__ = ("fingerprint", "patterns", "exclude", "ignore_case",
                 "service", "lane", "last_used", "pinned")

    def __init__(self, fingerprint: str, patterns: list[str],
                 exclude: list[str], ignore_case: bool,
                 service: AsyncFilterService, lane: _Lane,
                 pinned: bool = False) -> None:
        self.fingerprint = fingerprint
        self.patterns = patterns
        self.exclude = exclude
        self.ignore_case = ignore_case
        self.service = service
        self.lane = lane
        self.last_used = time.monotonic()
        # Pinned = the server's startup set: never evicted, and its
        # service is owned (and closed) by the server, not the registry.
        self.pinned = pinned

    def touch(self) -> None:
        self.last_used = time.monotonic()


class PatternSetRegistry:
    """Content-addressed pattern-set registry + tenant admission.

    ``register`` is single-flight per fingerprint: concurrent Register
    RPCs for the same set await one engine build (the compile runs off
    the event loop). Mutations of the registry maps go under ``_mut``
    (declared in tools/analysis lock-discipline SHARED_STATE): the maps
    are read by sync banner/Hello paths while async handlers register
    and evict."""

    def __init__(self, filter_factory: FilterFactory, *,
                 stats: Any = None,
                 max_sets: "int | None" = None,
                 quota_lines: "int | None" = None,
                 idle_evict_s: "float | None" = None,
                 slots: "int | None" = None) -> None:
        self._filter_factory = filter_factory
        self._stats = stats
        self._registry = stats.registry if stats is not None else None
        self.max_sets = (max_sets if max_sets is not None
                         else _env_int("KLOGS_TENANT_MAX_SETS",
                                       DEFAULT_MAX_SETS))
        self.quota_lines = (quota_lines if quota_lines is not None
                            else _env_int("KLOGS_TENANT_QUOTA_LINES",
                                          DEFAULT_QUOTA_LINES))
        self.idle_evict_s = (idle_evict_s if idle_evict_s is not None
                             else _env_float("KLOGS_TENANT_IDLE_S",
                                             DEFAULT_IDLE_EVICT_S))
        self._gate = FairGate(slots if slots is not None
                              else _env_int("KLOGS_TENANT_SLOTS",
                                            DEFAULT_SLOTS))
        # ONE fetch pool + ONE in-flight budget across every set: the
        # process owns one device; per-set pools would let one tenant
        # monopolize threads the fair gate never saw.
        self._pool = ThreadPoolExecutor(
            max_workers=DEFAULT_FETCH_WORKERS,
            thread_name_prefix="klogs-tenant-fetch")
        # Lazy (first Register runs on the loop): a Py3.10 asyncio
        # primitive binds the loop alive at construction, and the
        # registry may be built before serve() starts the real one.
        self._sem: "asyncio.Semaphore | None" = None
        self._mut = threading.Lock()
        self._sets: dict[str, SetEntry] = {}
        self._building: dict[str, asyncio.Future] = {}
        self._builds = 0
        self._closed = False
        self._m_sets: Any = None
        self._m_reg: Any = None
        self._m_builds: Any = None
        self._m_evict: Any = None
        self._m_wait: Any = None
        if self._registry is not None:
            r = self._registry
            self._m_sets = r.family("klogs_tenant_sets")
            self._m_reg = r.family("klogs_tenant_registrations_total")
            self._m_builds = r.family("klogs_tenant_engine_builds_total")
            self._m_evict = r.family("klogs_tenant_evictions_total")
            self._m_wait = r.family(
                "klogs_tenant_admission_wait_seconds")

    # -- introspection -------------------------------------------------

    @property
    def executor(self) -> ThreadPoolExecutor:
        """The ONE fetch pool every set's service shares — the server
        builds its pinned default-set service over it too, so legacy
        un-tagged traffic cannot double the device budget."""
        return self._pool

    @property
    def in_flight(self) -> asyncio.Semaphore:
        """The shared in-flight dispatch budget (see ``executor``),
        created on first use from the running loop."""
        if self._sem is None:
            self._sem = asyncio.Semaphore(DEFAULT_MAX_IN_FLIGHT)
        return self._sem

    @property
    def count(self) -> int:
        return len(self._sets)

    @property
    def engine_builds(self) -> int:
        """Engines compiled by this registry (test hook mirroring
        klogs_tenant_engine_builds_total): content-addressed reuse
        means a second registration of the same fingerprint must NOT
        advance this."""
        return self._builds

    def get(self, set_id: str) -> "SetEntry | None":
        return self._sets.get(set_id)

    def entries(self) -> "list[SetEntry]":
        """Point-in-time snapshot of the live entries (lock-free read,
        like every other registry read)."""
        return list(self._sets.values())

    def fingerprints(self) -> list[str]:
        return sorted(self._sets)

    # -- registration / eviction --------------------------------------

    async def register(self, patterns: Sequence[str],
                       exclude: "Sequence[str] | None" = None,
                       ignore_case: bool = False,
                       weight: float = 1.0) -> "tuple[str, bool]":
        """Register (or re-register) a pattern set. Returns
        ``(fingerprint, shared)`` — shared=True when the engine already
        existed (content-addressed reuse, no compile)."""
        if self._closed:
            raise RuntimeError("registry is closed")
        pats = [str(p) for p in patterns]
        excl = [str(p) for p in exclude or []]
        fp = pattern_fingerprint(pats, excl, ignore_case)
        while True:
            entry = self._sets.get(fp)
            if entry is not None:
                entry.touch()
                # Highest registered weight wins: a tenant asking for
                # more share must not be silently capped by whoever
                # registered the set first.
                if weight > entry.lane.weight:
                    entry.lane.weight = weight
                if self._m_reg is not None:
                    self._m_reg.labels(outcome="shared").inc()
                return fp, True
            fut = self._building.get(fp)
            if fut is not None:
                # Single-flight: ride the in-progress build, then loop
                # to pick the entry up (or surface the build error).
                # A _BuildCancelled means the builder died mid-compile
                # — loop and become the new builder; a CancelledError
                # is OUR OWN cancellation and propagates.
                try:
                    await asyncio.shield(fut)
                except _BuildCancelled:
                    pass
                continue
            break
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        # Retrieve a failed build's exception even when no concurrent
        # registrant awaited it (suppresses the never-retrieved warn).
        fut.add_done_callback(
            lambda f: f.exception() if not f.cancelled() else None)
        with self._mut:
            self._building[fp] = fut
        try:
            # The compile (regex parse, DFA determinization, index
            # build) is CPU-bound blocking work: off the loop, or one
            # tenant's 4k-pattern registration stalls every live
            # tenant's RPCs behind it.
            filt = await asyncio.to_thread(
                self._filter_factory, list(pats), list(excl), ignore_case)
            self._builds += 1
            service = AsyncFilterService(
                filt, stats=self._stats, executor=self._pool,
                in_flight=self.in_flight)
            lane = _Lane(fp, weight, self.quota_lines,
                         registry=self._registry)
            entry = SetEntry(fp, pats, excl, ignore_case, service, lane)
            with self._mut:
                self._sets[fp] = entry
            if self._m_builds is not None:
                self._m_builds.inc()
            if self._m_reg is not None:
                self._m_reg.labels(outcome="new").inc()
            if self._m_sets is not None:
                self._m_sets.set(len(self._sets))
            fut.set_result(fp)
        except BaseException as e:
            # Riders must see the builder's cancellation as the marker
            # type, never as a bare CancelledError they would mistake
            # for their own (see _BuildCancelled).
            fut.set_exception(
                _BuildCancelled() if isinstance(
                    e, asyncio.CancelledError) else e)
            raise
        finally:
            with self._mut:
                self._building.pop(fp, None)
        await self._evict_over_capacity()
        return fp, False

    def adopt(self, patterns: Sequence[str],
              exclude: "Sequence[str] | None",
              ignore_case: bool,
              service: AsyncFilterService) -> str:
        """Adopt the server's startup set (already compiled in
        FilterServer.__init__) as a pinned entry, so legacy un-tagged
        RPCs route through the same admission machinery while the
        single-set compile path stays byte-identical."""
        pats = [str(p) for p in patterns]
        excl = [str(p) for p in exclude or []]
        fp = pattern_fingerprint(pats, excl, ignore_case)
        lane = _Lane(fp, 1.0, self.quota_lines, registry=self._registry)
        entry = SetEntry(fp, pats, excl, ignore_case, service, lane,
                         pinned=True)
        with self._mut:
            self._sets[fp] = entry
        if self._m_sets is not None:
            self._m_sets.set(len(self._sets))
        return fp

    async def evict(self, fp: str, reason: str) -> bool:
        """Release one set's compiled engine. The DFA tables survive in
        the on-disk LRU (build_dfa_cached), so re-registration is a
        cache load, not a determinization."""
        entry = self._sets.get(fp)
        if entry is None or entry.pinned:
            return False
        with self._mut:
            self._sets.pop(fp, None)
        if self._registry is not None and reason != "shutdown":
            # Drop the evicted set's per-set series: the `set` label's
            # cardinality is bounded by LIVE sets, not lifetime churn —
            # without this, a long-lived registry cycling fingerprints
            # grows dead series (and a stale pending gauge) forever.
            # BEFORE the drain below: a transparent re-registration of
            # the same fingerprint can complete while the old service
            # drains, and removing afterwards would orphan the revived
            # lane's freshly created children. Shutdown skips removal:
            # the registry dies with the process and final counters
            # should stay scrapeable at teardown.
            for fam in ("klogs_tenant_shed_total",
                        "klogs_tenant_pending_lines",
                        "klogs_tenant_lines_total"):
                self._registry.family(fam).remove(set=fp)
        # Drain in-flight groups, close the engine; the SHARED fetch
        # pool survives (AsyncFilterService only shuts a pool it owns).
        await entry.service.aclose()
        if self._m_evict is not None:
            self._m_evict.labels(reason=reason).inc()
        if self._m_sets is not None:
            self._m_sets.set(len(self._sets))
        trace.TRACER.event("tenant.evict", tenant=fp, reason=reason)
        return True

    async def _evict_over_capacity(self) -> None:
        # The cap counts REGISTERED tenant sets only: the pinned
        # startup set rides free, or a max_sets=1 server with a default
        # set would evict every tenant the instant it registered — a
        # permanent register/FAILED_PRECONDITION loop.
        while sum(1 for e in self._sets.values()
                  if not e.pinned) > self.max_sets:
            victims = sorted(
                (e for e in self._sets.values() if not e.pinned),
                # Idle lanes first, then least-recently-used (the
                # just-registered entry carries the newest last_used,
                # so it is never its own victim).
                key=lambda e: (e.lane.pending_lines > 0, e.last_used))
            if not victims:
                return
            await self.evict(victims[0].fingerprint, "capacity")

    async def run_idle_sweeper(self, stop: asyncio.Event,
                               interval_s: "float | None" = None) -> None:
        """Periodic cold-set reaper; run as a background task on the
        server. Stop-aware wait (the blessed poller idiom)."""
        if self.idle_evict_s <= 0:
            return
        period = interval_s if interval_s is not None else max(
            self.idle_evict_s / 4.0, 0.05)
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=period)
                return
            except asyncio.TimeoutError:
                pass
            now = time.monotonic()
            for fp, entry in list(self._sets.items()):
                if (not entry.pinned and entry.lane.pending_lines == 0
                        and now - entry.last_used >= self.idle_evict_s):
                    try:
                        await self.evict(fp, "idle")
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001
                        # One failing engine teardown must not kill the
                        # sweeper for the rest of the run (cold sets
                        # would then silently pile up to the cap).
                        from klogs_tpu.ui import term

                        term.warning(
                            "tenant set %s idle eviction failed: %s",
                            fp, e)

    # -- admission + dispatch -----------------------------------------

    def _admit(self, set_id: str, n: int) -> SetEntry:
        entry = self._sets.get(set_id)
        if entry is None:
            raise SetNotRegistered(set_id)
        entry.touch()
        lane = entry.lane
        if n > 0 and lane.pending_lines + n > lane.quota_lines:
            if lane.m_shed is not None:
                lane.m_shed.inc()
            trace.TRACER.event("tenant.shed", tenant=set_id, lines=n,
                               pending=lane.pending_lines)
            raise OverQuota(
                f"set {set_id} over quota: {lane.pending_lines} lines "
                f"pending + {n} new > {lane.quota_lines} "
                "(KLOGS_TENANT_QUOTA_LINES)")
        return entry

    async def _dispatch(self, set_id: str, n: int,
                        run: "Callable[[SetEntry], Awaitable[Any]]"
                        ) -> Any:
        entry = self._admit(set_id, n)
        lane = entry.lane
        lane.note_pending(n)
        t0 = time.perf_counter()
        try:
            # The tenant attr is what lets a flight-recorder dump or
            # --trace-json stream attribute a stall to the offending
            # set (satellite: span tenant attribution).
            with trace.TRACER.span("tenant.admit", tenant=set_id,
                                   lines=n) as sp:
                async with self._gate.slot(lane, max(n, 1)):
                    wait = time.perf_counter() - t0
                    sp.set_attr("admission_wait_s", wait)
                    if self._m_wait is not None:
                        self._m_wait.observe(wait)
                    if lane.m_lines is not None:
                        lane.m_lines.inc(n)
                    try:
                        return await run(entry)
                    except RuntimeError as e:
                        # Exact sentinel only: a device/channel
                        # RuntimeError that merely mentions "closed"
                        # is a real failure, not an eviction, and must
                        # not be masked as re-register-and-retry.
                        if str(e) == "AsyncFilterService is closed":
                            # Admission raced an eviction: the entry was
                            # live at _admit but its service closed
                            # before dispatch. Same contract as a fully
                            # evicted set — the client re-registers.
                            raise SetNotRegistered(set_id) from e
                        raise
        finally:
            lane.note_pending(-n)

    async def match_framed(self, set_id: str, payload: bytes,
                           offsets: Any) -> Any:
        n = max(len(offsets) - 1, 0)
        return await self._dispatch(
            set_id, n,
            lambda e: e.service.match_framed(payload, offsets))

    async def match(self, set_id: str, lines: "list[bytes]"
                    ) -> "list[bool]":
        return await self._dispatch(
            set_id, len(lines), lambda e: e.service.match(lines))

    # -- teardown -----------------------------------------------------

    async def aclose(self) -> None:
        self._closed = True
        for fp, entry in list(self._sets.items()):
            if entry.pinned:
                # The server owns (and closes) its startup service.
                with self._mut:
                    self._sets.pop(fp, None)
                continue
            await self.evict(fp, "shutdown")
        await asyncio.to_thread(self._pool.shutdown)

    def close(self) -> None:
        self._closed = True
        for fp, entry in list(self._sets.items()):
            with self._mut:
                self._sets.pop(fp, None)
            if not entry.pinned:
                entry.service.close()
        self._pool.shutdown(wait=True)
