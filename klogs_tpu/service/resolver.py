"""Pluggable endpoint resolvers — live fleet membership for the
sharded tier.

A static ``--remote`` list is a deployment frozen at invocation time:
a rolling restart, a scale-up, or a node loss all require restarting
the collector. A ``Resolver`` closes that gap: it is polled on a fixed
cadence (``KLOGS_RESOLVER_INTERVAL_S``) by ``ShardedFilterClient``'s
background prober and returns the fleet's CURRENT endpoint list; the
client diffs it against live membership and applies adds/removes under
a ring-generation guard (``shard.py:apply_membership``). Every joiner
enters unverified — the existing verify-before-rejoin quarantine
(Hello handshake; drifted pattern set ⇒ permanent quarantine) runs
before it receives a single batch.

Kinds (the ``--resolver`` spec grammar):

- ``static:HOST:PORT[,...]`` — a fixed list, byte-identical in effect
  to today's ``--remote`` (exists so the plumbing is testable and so
  configs can switch kinds without changing shape).
- ``file:/path`` — one endpoint per line (``#`` comments and blank
  lines ignored), re-read each poll. The operator's hand-rolled
  service discovery: edit the file, the fleet follows.
- ``dns:HOST:PORT`` — re-resolve HOST each poll (getaddrinfo); every
  A/AAAA record becomes ``ip:PORT``. Headless-service style discovery
  without the Kubernetes API.
- ``kube:NAMESPACE/NAME[:PORT]`` — list the named Endpoints object
  through ``cluster/kube.py``'s apiserver client (same retry policy,
  token refresh, and TLS the pod discovery path uses). Without
  ``:PORT`` the subset's advertised port is used.

Contract: ``resolve()`` is async and returns the full current target
list (a snapshot, not a delta — the differ lives client-side, so a
missed poll never desynchronizes membership). A transient failure
raises ``ResolverError``; the poller logs it, counts a membership
``error`` event, and keeps the current fleet — discovery hiccups must
never drop a healthy endpoint. The ``resolver.watch`` fault point
wraps every poll, so chaos scripts drive this exact recovery path.

This module imports no transport machinery (no grpc, no aiohttp) at
module level: spec parsing must work wherever the CLI does.
"""

import asyncio
from typing import Any, Callable

from klogs_tpu.resilience import FAULTS

RESOLVER_KINDS = ("static", "file", "dns", "kube")
DEFAULT_RESOLVE_INTERVAL_S = 5.0


class ResolverError(RuntimeError):
    """A transient resolution failure (unreadable file, DNS timeout,
    apiserver weather): the poller keeps the current membership and
    retries next interval. Configuration errors (bad spec, bad
    kubeconfig) raise ValueError instead and fail startup loudly."""


def split_spec(spec: str) -> "tuple[str, str]":
    """``KIND:REST`` with a registered kind, or ValueError naming the
    bad spec — the CLI-side validation (grammar only; no I/O)."""
    kind, sep, rest = spec.partition(":")
    if not sep or kind not in RESOLVER_KINDS:
        raise ValueError(
            f"malformed --resolver spec {spec!r} "
            f"(want one of: {', '.join(k + ':...' for k in RESOLVER_KINDS)})")
    if not rest:
        raise ValueError(f"--resolver spec {spec!r} names no target")
    return kind, rest


class Resolver:
    """Base contract. Subclasses implement ``_resolve``; the public
    ``resolve`` wraps it in the ``resolver.watch`` fault point so an
    armed chaos script exercises the real keep-current-fleet path."""

    kind: str = "?"

    def describe(self) -> str:
        return self.kind

    async def resolve(self) -> "list[str]":
        if FAULTS.active:
            await FAULTS.fire("resolver.watch")
        return await self._resolve()

    async def _resolve(self) -> "list[str]":
        raise NotImplementedError

    async def aclose(self) -> None:  # noqa: B027 — default no-op
        """Release any discovery-side resources (the kube resolver's
        apiserver session). Owned and awaited by the sharded client's
        own aclose."""


class StaticResolver(Resolver):
    """A fixed list — membership never changes, the poll is a no-op
    diff. Exists so ``--resolver static:...`` behaves exactly like
    ``--remote`` and the plumbing stays testable end to end."""

    kind = "static"

    def __init__(self, targets: "list[str]") -> None:
        if not targets:
            raise ValueError("static resolver needs at least one endpoint")
        self._targets = list(targets)

    def describe(self) -> str:
        return f"static:{','.join(self._targets)}"

    async def _resolve(self) -> "list[str]":
        return list(self._targets)


class FileResolver(Resolver):
    """One endpoint per line; ``#`` starts a comment, blank lines are
    skipped. Re-read every poll (no inotify dependency — the poll
    cadence IS the watch). An unreadable file is transient: the fleet
    keeps flying on current membership while the operator fixes it."""

    kind = "file"

    def __init__(self, path: str) -> None:
        if not path:
            raise ValueError("file resolver needs a path")
        self._path = path

    def describe(self) -> str:
        return f"file:{self._path}"

    def _read(self) -> "list[str]":
        try:
            with open(self._path, encoding="utf-8") as f:
                raw = f.read()
        except OSError as e:
            raise ResolverError(
                f"cannot read resolver file {self._path}: {e}") from e
        targets: "list[str]" = []
        for line in raw.splitlines():
            entry = line.split("#", 1)[0].strip()
            if entry:
                targets.append(entry)
        return targets

    async def _resolve(self) -> "list[str]":
        # File I/O off the event loop: NFS/overlay mounts can stall.
        return await asyncio.to_thread(self._read)


class DnsResolver(Resolver):
    """Re-resolve one name to the full A/AAAA record set each poll —
    headless-Service/round-robin-DNS discovery. ``resolve_fn`` injects
    a fake for tests (the default is ``socket.getaddrinfo``)."""

    kind = "dns"

    def __init__(self, host: str, port: int,
                 resolve_fn: "Callable[[str], list[str]] | None" = None
                 ) -> None:
        if not host:
            raise ValueError("dns resolver needs HOST:PORT")
        if not 0 < port < 65536:
            raise ValueError(f"dns resolver: bad port {port!r}")
        self._host = host
        self._port = port
        self._resolve_fn = resolve_fn

    def describe(self) -> str:
        return f"dns:{self._host}:{self._port}"

    def _lookup(self) -> "list[str]":
        if self._resolve_fn is not None:
            addrs = self._resolve_fn(self._host)
        else:
            import socket

            try:
                infos = socket.getaddrinfo(self._host, self._port,
                                           type=socket.SOCK_STREAM)
            except OSError as e:
                raise ResolverError(
                    f"DNS resolution of {self._host} failed: {e}") from e
            addrs = [info[4][0] for info in infos]
        targets: "list[str]" = []
        for addr in addrs:
            host = f"[{addr}]" if ":" in addr else addr
            targets.append(f"{host}:{self._port}")
        return targets

    async def _resolve(self) -> "list[str]":
        # getaddrinfo blocks (glibc has no async path): worker thread.
        return await asyncio.to_thread(self._lookup)


class KubeEndpointsResolver(Resolver):
    """List a Kubernetes Endpoints object through the same apiserver
    client the pod-discovery path uses — shared RetryPolicy, one-shot
    401 token refresh, TLS from the kubeconfig. The backend is built
    lazily on the first poll (inside the running loop — the aiohttp
    session must bind there, and the collector may never poll if it
    exits first); ``backend_factory`` injects a fake for tests."""

    kind = "kube"

    def __init__(self, namespace: str, name: str,
                 port: "int | None" = None,
                 kubeconfig: "str | None" = None,
                 backend_factory: "Callable[[], Any] | None" = None
                 ) -> None:
        if not namespace or not name:
            raise ValueError(
                "kube resolver needs NAMESPACE/NAME[:PORT]")
        if port is not None and not 0 < port < 65536:
            raise ValueError(f"kube resolver: bad port {port!r}")
        self._namespace = namespace
        self._name = name
        self._port = port
        self._kubeconfig = kubeconfig
        self._backend_factory = backend_factory
        self._backend: Any = None

    def describe(self) -> str:
        suffix = f":{self._port}" if self._port is not None else ""
        return f"kube:{self._namespace}/{self._name}{suffix}"

    async def _ensure_backend(self) -> Any:
        if self._backend is None:
            if self._backend_factory is not None:
                self._backend = self._backend_factory()
            else:
                from klogs_tpu.cluster.kube import KubeBackend
                from klogs_tpu.cluster.kubeconfig import (
                    KubeconfigError,
                    load_creds,
                )

                try:
                    self._backend = KubeBackend(
                        load_creds(self._kubeconfig))
                except KubeconfigError as e:
                    # Credentials may appear later (a projected token
                    # still mounting): transient, retried next poll.
                    raise ResolverError(str(e)) from e
        return self._backend

    async def _resolve(self) -> "list[str]":
        from klogs_tpu.cluster.backend import ClusterError

        backend = await self._ensure_backend()
        try:
            doc = await backend.endpoint_addresses(
                self._namespace, self._name)
        except ClusterError as e:
            raise ResolverError(str(e)) from e
        targets: "list[str]" = []
        for ip, port in doc:
            use = self._port if self._port is not None else port
            if use is None:
                raise ResolverError(
                    f"Endpoints {self._namespace}/{self._name} "
                    f"advertises no port for {ip} and the --resolver "
                    "spec pins none")
            host = f"[{ip}]" if ":" in ip else ip
            targets.append(f"{host}:{use}")
        return targets

    async def aclose(self) -> None:
        backend, self._backend = self._backend, None
        if backend is not None:
            await backend.close()


def make_resolver(spec: str,
                  kubeconfig: "str | None" = None) -> Resolver:
    """Build a resolver from a ``--resolver`` spec. Grammar errors
    raise ValueError naming the spec (the pipeline wraps them in the
    CLI's friendly fatal path); I/O happens only at poll time."""
    kind, rest = split_spec(spec)
    if kind == "static":
        targets = [t.strip() for t in rest.split(",") if t.strip()]
        return StaticResolver(targets)
    if kind == "file":
        return FileResolver(rest)
    if kind == "dns":
        host, sep, port = rest.rpartition(":")
        if not sep or not host or not port.isdigit():
            raise ValueError(
                f"--resolver dns spec {spec!r}: want dns:HOST:PORT")
        return DnsResolver(host, int(port))
    # kube:NAMESPACE/NAME[:PORT]
    body, sep, port_s = rest.rpartition(":")
    port: "int | None" = None
    if sep and port_s.isdigit():
        port = int(port_s)
    else:
        body = rest
    namespace, sep, name = body.partition("/")
    if not sep or not namespace or not name:
        raise ValueError(
            f"--resolver kube spec {spec!r}: want "
            "kube:NAMESPACE/NAME[:PORT]")
    return KubeEndpointsResolver(namespace, name, port=port,
                                 kubeconfig=kubeconfig)
