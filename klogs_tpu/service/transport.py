"""gRPC transport for the remote filter service.

North-star role: the host->TPU-process batch boundary ("ships batches
over gRPC to a co-located JAX process"). The log-collecting process —
which may be anywhere a kubeconfig works — sends line batches; the
process that owns the TPU (jax initialized once, kernels warm) returns
keep-masks. The service end coalesces batches across ALL clients via
AsyncFilterService, so many small collectors still produce jumbo device
batches.

Wire format: gRPC generic methods (no protoc codegen — the environment
has grpcio but not grpcio-tools) with msgpack bodies:

  /klogs.Filter/Hello   {} -> {"patterns": [...], "backend": str,
                               "version": str}
  /klogs.Filter/Match   {"lines": [bytes, ...]} -> {"mask": bytes}
                        (mask[i] == 1 -> keep lines[i])

Clients verify Hello.patterns against their own --match set, failing
fast on mismatched deployments rather than silently filtering with the
wrong patterns.

The reference's closest analog is its apiserver REST client
(/root/reference/cmd/root.go:322-325) — the one network boundary in
that design; this is the second boundary the TPU architecture adds.
"""

import msgpack

SERVICE = "klogs.Filter"
HELLO = f"/{SERVICE}/Hello"
MATCH = f"/{SERVICE}/Match"


def pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes):
    return msgpack.unpackb(data, raw=False)


def encode_match_request(lines: list[bytes]) -> bytes:
    return pack({"lines": lines})


def decode_match_request(data: bytes) -> list[bytes]:
    return unpack(data)["lines"]


def encode_match_response(mask: list[bool]) -> bytes:
    return pack({"mask": bytes(bytearray(mask))})


def decode_match_response(data: bytes) -> list[bool]:
    return [bool(b) for b in unpack(data)["mask"]]
