"""gRPC transport for the remote filter service.

North-star role: the host->TPU-process batch boundary ("ships batches
over gRPC to a co-located JAX process"). The log-collecting process —
which may be anywhere a kubeconfig works — sends line batches; the
process that owns the TPU (jax initialized once, kernels warm) returns
keep-masks. The service end coalesces batches across ALL clients via
AsyncFilterService, so many small collectors still produce jumbo device
batches.

Wire format: gRPC generic methods (no protoc codegen — the environment
has grpcio but not grpcio-tools) with msgpack bodies:

  /klogs.Filter/Hello   {} -> {"patterns": [...], "backend": str,
                               "version": str}
  /klogs.Filter/Match   {"lines": [bytes, ...]} -> {"mask": bytes}
                        (mask[i] == 1 -> keep lines[i])

Clients verify Hello.patterns against their own --match set, failing
fast on mismatched deployments rather than silently filtering with the
wrong patterns.

The reference's closest analog is its apiserver REST client
(/root/reference/cmd/root.go:322-325) — the one network boundary in
that design; this is the second boundary the TPU architecture adds.
"""

from typing import TYPE_CHECKING, Any

import msgpack

if TYPE_CHECKING:
    import numpy

SERVICE = "klogs.Filter"
HELLO = f"/{SERVICE}/Hello"
MATCH = f"/{SERVICE}/Match"
MATCH_FRAMED = f"/{SERVICE}/MatchFramed"
# Multi-tenant registry (docs/TENANCY.md): a collector registers its
# pattern set once (content-addressed by fingerprint) and tags every
# later Match/MatchFramed with the returned set id. Only servers whose
# Hello advertises multi_set are ever sent a Register — a single-set
# server keeps the strict pattern-comparison handshake and never sees
# the method (its UNIMPLEMENTED answer would be a fatal config error,
# by design).
REGISTER = f"/{SERVICE}/Register"
# Stable machine-readable prefixes on tenant-path error details. Part
# of the wire contract — the client keys its behavior on THESE tokens,
# never on the human-readable prose after them (which may be reworded
# across versions) and never on the bare status code (gRPC itself
# emits RESOURCE_EXHAUSTED for oversize messages, which is NOT a
# quota shed).
# FAILED_PRECONDITION: the registry does not hold the named set
# (evicted or never registered) -> client re-registers and retries.
SET_NOT_REGISTERED = "set-not-registered"
# RESOURCE_EXHAUSTED: the set's lane is over its pending-line quota ->
# client raises the degradeable ShedByServer.
OVER_QUOTA = "tenant-over-quota"

# Trace-context propagation (obs.trace): the collector's batch trace
# crosses this boundary as one metadata entry, W3C traceparent format
# (00-<32hex trace>-<16hex span>-<2hex flags>), so a filterd's server
# spans parent under the collector's RPC span. Part of the wire
# contract like the method names above; servers without the key root
# their own traces, clients never require it be honored.
from klogs_tpu.obs.trace import TRACEPARENT_KEY  # noqa: E402


def trace_metadata() -> "tuple[tuple[str, str], ...]":
    """Metadata entries carrying the CURRENT span context (empty when
    nothing records) — what the client appends to each RPC."""
    from klogs_tpu.obs.trace import TRACER

    return TRACER.inject()


def extract_trace(metadata: "Any") -> "Any":
    """Invocation metadata -> SpanContext | None — what the server
    hands to ``tracer.span(..., parent=...)``."""
    from klogs_tpu.obs.trace import TRACER

    return TRACER.extract(metadata)


def pack(obj: object) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)


def encode_match_request(lines: list[bytes],
                         set_id: "str | None" = None) -> bytes:
    doc: "dict[str, Any]" = {"lines": lines}
    if set_id is not None:
        doc["set"] = set_id
    return pack(doc)


def decode_match_request(data: bytes) -> "tuple[list[bytes], str | None]":
    doc = unpack(data)
    return doc["lines"], _set_id(doc)


def _set_id(doc: "dict[str, Any]") -> "str | None":
    """Optional tenant set id on a match request. Validated here: a
    non-string set would otherwise surface as an obscure KeyError deep
    in the registry."""
    set_id = doc.get("set")
    if set_id is not None and not isinstance(set_id, str):
        raise ValueError(
            f"match request: set id must be a string, got "
            f"{type(set_id).__name__}")
    return set_id


# -- registration (multi-tenant servers) ------------------------------

def encode_register_request(patterns: "list[str]",
                            exclude: "list[str] | None" = None,
                            ignore_case: bool = False,
                            weight: float = 1.0) -> bytes:
    return pack({"patterns": list(patterns),
                 "exclude": list(exclude or []),
                 "ignore_case": bool(ignore_case),
                 "weight": float(weight)})


def decode_register_request(data: bytes) -> "dict[str, Any]":
    doc = unpack(data)
    patterns = doc.get("patterns")
    exclude = doc.get("exclude", [])
    if not isinstance(patterns, list) or not all(
            isinstance(p, str) for p in patterns):
        raise ValueError("register request: patterns must be a list of "
                         "strings")
    if not isinstance(exclude, list) or not all(
            isinstance(p, str) for p in exclude):
        raise ValueError("register request: exclude must be a list of "
                         "strings")
    if not patterns and not exclude:
        raise ValueError("register request: need at least one pattern")
    weight = doc.get("weight", 1.0)
    if not isinstance(weight, (int, float)) or not (0 < float(weight)
                                                    <= 1024):
        raise ValueError(
            f"register request: weight must be in (0, 1024], got "
            f"{weight!r}")
    return {"patterns": patterns, "exclude": exclude,
            "ignore_case": bool(doc.get("ignore_case", False)),
            "weight": float(weight)}


def encode_register_response(set_id: str, shared: bool,
                             sets: int) -> bytes:
    return pack({"set": set_id, "shared": shared, "sets": sets})


def decode_register_response(data: bytes) -> "dict[str, Any]":
    doc = unpack(data)
    if not isinstance(doc.get("set"), str):
        raise ValueError("register response: missing set id")
    return doc


def encode_hello_request(patterns: "list[str] | None" = None,
                         exclude: "list[str] | None" = None,
                         ignore_case: bool = False) -> bytes:
    """Hello with the collector's invocation attached: a multi-set
    server answers verify_patterns against its REGISTRY (matching the
    request's fingerprint) instead of one fixed startup list. An empty
    body keeps the legacy handshake; old servers ignore any body."""
    if patterns is None and not exclude:
        return b""
    return pack({"patterns": list(patterns or []),
                 "exclude": list(exclude or []),
                 "ignore_case": bool(ignore_case)})


def decode_hello_request(data: bytes) -> "dict[str, Any] | None":
    """-> the collector's invocation, or None for the legacy empty
    Hello. Malformed bodies are treated as legacy (old clients may
    send arbitrary ignored payloads; the handshake must not break)."""
    if not data:
        return None
    try:
        doc = unpack(data)
    except Exception:
        return None
    if not isinstance(doc, dict) or "patterns" not in doc:
        return None
    return {"patterns": [str(p) for p in doc.get("patterns") or []],
            "exclude": [str(p) for p in doc.get("exclude") or []],
            "ignore_case": bool(doc.get("ignore_case", False))}


def encode_match_response(mask: list[bool]) -> bytes:
    return pack({"mask": bytes(bytearray(mask))})


def decode_match_response(data: bytes) -> list[bool]:
    return [bool(b) for b in unpack(data)["mask"]]


# -- framed protocol --------------------------------------------------
# MatchFramed ships ONE contiguous payload + an int32[n+1] offsets
# array (three msgpack bin fields — O(1) encode/decode per batch)
# instead of a per-line bin list, and the response mask comes back as a
# raw uint8 buffer. The per-line msgpack objects of the legacy Match
# were the measured transport bottleneck on a shared single core
# (~1us/line across client+server; SERVICE_BENCH.json round-4 rows vs
# the 9.8M lines/s in-process engine). Hello advertises
# {"framed": True}; clients fall back to Match against older servers.

def encode_framed_request(payload: bytes,
                          offsets: "numpy.ndarray",
                          set_id: "str | None" = None) -> bytes:
    import numpy as np

    offs = np.ascontiguousarray(offsets, dtype=np.int32)
    doc: "dict[str, Any]" = {"n": len(offs) - 1, "offs": offs.tobytes(),
                             "data": payload}
    if set_id is not None:
        doc["set"] = set_id
    return pack(doc)


def decode_framed_request(
        data: bytes) -> "tuple[bytes, numpy.ndarray, str | None]":
    """-> (payload: bytes, offsets: int32 np.ndarray[n+1],
    set_id: str | None — the tenant set lane on multi-set servers).

    Validates the offsets array fully: the server feeds it into a
    coalescer SHARED across all connected collectors, so one client's
    malformed offsets must fail its own RPC here — not poison the
    group batch (mis-sliced verdicts / exceptions for innocent
    callers)."""
    import numpy as np

    doc = unpack(data)
    n = int(doc["n"])
    payload = doc["data"]
    if not isinstance(payload, (bytes, bytearray)):
        # A msgpack str payload passes every offset check below
        # (len() works on str) and would only blow up INSIDE the
        # shared coalescer, failing innocent callers' RPCs (ADVICE
        # r5, confirmed repro). Type-check here so it fails its own.
        raise ValueError(
            f"framed request: payload must be bytes, got "
            f"{type(payload).__name__}")
    if not isinstance(doc["offs"], (bytes, bytearray)):
        raise ValueError(
            f"framed request: offs must be bytes, got "
            f"{type(doc['offs']).__name__}")
    offsets = np.frombuffer(doc["offs"], dtype=np.int32)
    if n < 0 or len(offsets) != n + 1:
        raise ValueError(
            f"framed request: {len(offsets)} offsets for n={n}")
    if len(offsets) and (
            int(offsets[0]) != 0
            or int(offsets[-1]) != len(payload)
            or bool((np.diff(offsets) < 0).any())):
        raise ValueError("framed request: offsets must rise from 0 to "
                         "len(payload) monotonically")
    return payload, offsets, _set_id(doc)


def encode_framed_response(mask: "numpy.ndarray") -> bytes:
    """mask: numpy bool/uint8 array -> raw byte-per-verdict body."""
    import numpy as np

    return pack({"mask": np.ascontiguousarray(
        mask, dtype=np.uint8).tobytes()})


def decode_framed_response(data: bytes) -> "numpy.ndarray":
    """-> numpy bool verdict array (no per-line Python objects)."""
    import numpy as np

    return np.frombuffer(unpack(data)["mask"], dtype=np.uint8).astype(bool)
