"""gRPC transport for the remote filter service.

North-star role: the host->TPU-process batch boundary ("ships batches
over gRPC to a co-located JAX process"). The log-collecting process —
which may be anywhere a kubeconfig works — sends line batches; the
process that owns the TPU (jax initialized once, kernels warm) returns
keep-masks. The service end coalesces batches across ALL clients via
AsyncFilterService, so many small collectors still produce jumbo device
batches.

Wire format: gRPC generic methods (no protoc codegen — the environment
has grpcio but not grpcio-tools) with msgpack bodies:

  /klogs.Filter/Hello   {} -> {"patterns": [...], "backend": str,
                               "version": str}
  /klogs.Filter/Match   {"lines": [bytes, ...]} -> {"mask": bytes}
                        (mask[i] == 1 -> keep lines[i])

Clients verify Hello.patterns against their own --match set, failing
fast on mismatched deployments rather than silently filtering with the
wrong patterns.

The reference's closest analog is its apiserver REST client
(/root/reference/cmd/root.go:322-325) — the one network boundary in
that design; this is the second boundary the TPU architecture adds.
"""

from typing import TYPE_CHECKING, Any

import msgpack

if TYPE_CHECKING:
    import numpy

SERVICE = "klogs.Filter"
HELLO = f"/{SERVICE}/Hello"
MATCH = f"/{SERVICE}/Match"
MATCH_FRAMED = f"/{SERVICE}/MatchFramed"

# Trace-context propagation (obs.trace): the collector's batch trace
# crosses this boundary as one metadata entry, W3C traceparent format
# (00-<32hex trace>-<16hex span>-<2hex flags>), so a filterd's server
# spans parent under the collector's RPC span. Part of the wire
# contract like the method names above; servers without the key root
# their own traces, clients never require it be honored.
from klogs_tpu.obs.trace import TRACEPARENT_KEY  # noqa: E402


def trace_metadata() -> "tuple[tuple[str, str], ...]":
    """Metadata entries carrying the CURRENT span context (empty when
    nothing records) — what the client appends to each RPC."""
    from klogs_tpu.obs.trace import TRACER

    return TRACER.inject()


def extract_trace(metadata: "Any") -> "Any":
    """Invocation metadata -> SpanContext | None — what the server
    hands to ``tracer.span(..., parent=...)``."""
    from klogs_tpu.obs.trace import TRACER

    return TRACER.extract(metadata)


def pack(obj: object) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    return msgpack.unpackb(data, raw=False)


def encode_match_request(lines: list[bytes]) -> bytes:
    return pack({"lines": lines})


def decode_match_request(data: bytes) -> list[bytes]:
    return unpack(data)["lines"]


def encode_match_response(mask: list[bool]) -> bytes:
    return pack({"mask": bytes(bytearray(mask))})


def decode_match_response(data: bytes) -> list[bool]:
    return [bool(b) for b in unpack(data)["mask"]]


# -- framed protocol --------------------------------------------------
# MatchFramed ships ONE contiguous payload + an int32[n+1] offsets
# array (three msgpack bin fields — O(1) encode/decode per batch)
# instead of a per-line bin list, and the response mask comes back as a
# raw uint8 buffer. The per-line msgpack objects of the legacy Match
# were the measured transport bottleneck on a shared single core
# (~1us/line across client+server; SERVICE_BENCH.json round-4 rows vs
# the 9.8M lines/s in-process engine). Hello advertises
# {"framed": True}; clients fall back to Match against older servers.

def encode_framed_request(payload: bytes,
                          offsets: "numpy.ndarray") -> bytes:
    import numpy as np

    offs = np.ascontiguousarray(offsets, dtype=np.int32)
    return pack({"n": len(offs) - 1, "offs": offs.tobytes(),
                 "data": payload})


def decode_framed_request(data: bytes) -> "tuple[bytes, numpy.ndarray]":
    """-> (payload: bytes, offsets: int32 np.ndarray[n+1]).

    Validates the offsets array fully: the server feeds it into a
    coalescer SHARED across all connected collectors, so one client's
    malformed offsets must fail its own RPC here — not poison the
    group batch (mis-sliced verdicts / exceptions for innocent
    callers)."""
    import numpy as np

    doc = unpack(data)
    n = int(doc["n"])
    payload = doc["data"]
    if not isinstance(payload, (bytes, bytearray)):
        # A msgpack str payload passes every offset check below
        # (len() works on str) and would only blow up INSIDE the
        # shared coalescer, failing innocent callers' RPCs (ADVICE
        # r5, confirmed repro). Type-check here so it fails its own.
        raise ValueError(
            f"framed request: payload must be bytes, got "
            f"{type(payload).__name__}")
    if not isinstance(doc["offs"], (bytes, bytearray)):
        raise ValueError(
            f"framed request: offs must be bytes, got "
            f"{type(doc['offs']).__name__}")
    offsets = np.frombuffer(doc["offs"], dtype=np.int32)
    if n < 0 or len(offsets) != n + 1:
        raise ValueError(
            f"framed request: {len(offsets)} offsets for n={n}")
    if len(offsets) and (
            int(offsets[0]) != 0
            or int(offsets[-1]) != len(payload)
            or bool((np.diff(offsets) < 0).any())):
        raise ValueError("framed request: offsets must rise from 0 to "
                         "len(payload) monotonically")
    return payload, offsets


def encode_framed_response(mask: "numpy.ndarray") -> bytes:
    """mask: numpy bool/uint8 array -> raw byte-per-verdict body."""
    import numpy as np

    return pack({"mask": np.ascontiguousarray(
        mask, dtype=np.uint8).tobytes()})


def decode_framed_response(data: bytes) -> "numpy.ndarray":
    """-> numpy bool verdict array (no per-line Python objects)."""
    import numpy as np

    return np.frombuffer(unpack(data)["mask"], dtype=np.uint8).astype(bool)
