"""Sharded filterd tier: one collector, N filter servers.

``ShardedFilterClient`` wraps one ``RemoteFilterClient`` per endpoint
behind the exact client API the sink layer already speaks (``hello`` /
``verify_patterns`` / ``match`` / ``match_framed`` / ``aclose``), so a
fleet drops in wherever a single ``--remote`` server did. What it adds
is the part the paper's single-endpoint pipeline could not have: a
*dead or draining* server becomes a routing event instead of an outage.

Mechanisms, in the order a batch meets them:

- **Live membership** (``--resolver``): a pluggable resolver
  (service/resolver.py: static list, watched file, DNS re-resolution,
  Kubernetes Endpoints) is polled on the prober cadence
  (``KLOGS_RESOLVER_INTERVAL_S``) and its snapshot diffed into the
  fleet by ``apply_membership`` under a ring-generation guard: a
  dispatch that observes the generation move mid-batch re-routes
  against fresh membership instead of finishing a stale candidate
  walk. Joiners enter UNVERIFIED — the same verify-before-rejoin
  quarantine that guards restarts (Hello handshake; drifted set ⇒
  permanent quarantine) must pass before a joiner sees a batch — and
  an empty or failed resolution keeps the current fleet (discovery
  hiccups must never drop healthy endpoints).
- **Routing** (``--shard-mode``): ``round-robin`` rotates the fleet per
  batch; ``hash`` pins the pattern-set fingerprint to an owner on a
  consistent-hash ring (virtual nodes), so identical collectors
  converge on the same server — maximizing that server's coalescer and
  compile-cache locality — and an endpoint loss moves only the keys it
  owned.
- **Capacity weighting** (round-robin mode): each endpoint's
  Hello-advertised headroom becomes a routing weight (floor 0.05 — a
  saturated server still gets a trickle and stays a failover
  candidate), applied by deterministic smooth weighted round-robin so
  a slow endpoint receives proportionally fewer batches. Weights decay
  toward uniform as their advertisement ages
  (``KLOGS_WEIGHT_DECAY_S``; 0 disables weighting): a silent prober
  must not let a stale low weight starve a now-healthy node. Hash mode
  stays pinned (locality IS its policy); breaker/readyz demotions
  compose — weights order the healthy set, demoted endpoints stay
  last-resort.
- **Per-endpoint breakers**: each inner client carries its own
  ``CircuitBreaker`` (``rpc@host:port``). An open breaker demotes the
  endpoint to last-resort; its fast-fail (no wire traffic) is what
  keeps a dead server from costing every flush a retry tower.
- **Readiness drain**: endpoints that advertise a metrics port in
  their Hello get their ``/readyz`` polled in the background. A
  draining/restarting server (readiness 503, or nothing listening) is
  routed around BEFORE its RPCs start failing, and rejoins the
  rotation the moment ``/readyz`` answers 200 again.
- **Hedged dispatch**: if the primary attempt has not resolved within
  ``hedge_s``, the same batch is raced against the next sibling (and
  another each further ``hedge_s``). First success wins; losers are
  cancelled promptly and never double-count anywhere — the sink
  records exactly one result per batch.
- **Failover**: an endpoint whose attempt terminates ``Unavailable``
  (retries exhausted / breaker open) is skipped and the next candidate
  tried. Only when EVERY endpoint has failed does the dispatch raise
  ``Unavailable`` — the type ``--on-filter-error`` degrade routing
  catches — so partial-fleet failure never degrades a single batch.
"""

import asyncio
import bisect
import hashlib
import time
from typing import TYPE_CHECKING, Any, Awaitable, Callable, Iterable, Sequence

if TYPE_CHECKING:
    from klogs_tpu.service.resolver import Resolver

from klogs_tpu.obs import trace
from klogs_tpu.resilience import (
    BREAKER_OPEN,
    BreakerOpen,
    CircuitBreaker,
    Unavailable,
)
from klogs_tpu.service.client import (
    PatternMismatch,
    RemoteFilterClient,
    ServiceConfigError,
    check_server_config,
)
from klogs_tpu.ui import term

SHARD_MODES = ("round-robin", "hash")

# Hedge a batch against a sibling when the primary has not resolved in
# this long (KLOGS_HEDGE_S overrides via make_pipeline). Batches run in
# milliseconds against a healthy filterd: a second of silence means the
# server is compiling, draining, or gone — all cases where racing a
# sibling beats waiting out the primary's full retry tower.
DEFAULT_HEDGE_S = 1.0
DEFAULT_PROBE_INTERVAL_S = 1.0
DEFAULT_PROBE_TIMEOUT_S = 1.0
# How often the prober refreshes each endpoint's capacity
# advertisement (headroom + offered/admitted totals from Hello) for
# the collector-side klogs_fleet_endpoint_* re-export
# (KLOGS_FLEET_REFRESH_S overrides).
DEFAULT_CAPACITY_REFRESH_S = 5.0

# Virtual nodes per endpoint on the consistent-hash ring: enough that
# removing one of a handful of endpoints re-homes its keys roughly
# evenly across the survivors.
_RING_VNODES = 64

# Capacity-weighted routing: how long a Hello-advertised headroom
# stays fully trusted before decaying linearly toward uniform
# (KLOGS_WEIGHT_DECAY_S overrides; 0 disables weighting entirely).
DEFAULT_WEIGHT_DECAY_S = 30.0
# A saturated endpoint (headroom 0) keeps this floor weight: it must
# stay a live failover candidate and receive the occasional batch so
# its recovery is ever observed through the dispatch path itself.
_WEIGHT_FLOOR = 0.05


def parse_endpoints(spec: str) -> list[str]:
    """Split a comma-separated ``--remote`` list and validate every
    entry up front: a malformed target must fail naming itself at
    startup, not as a late gRPC error mid-stream."""
    targets: list[str] = []
    seen: set[str] = set()
    for raw in spec.split(","):
        target = raw.strip()
        if not target:
            raise ServiceConfigError(
                f"--remote list {spec!r} contains an empty entry")
        _validate_target(target)
        if target in seen:
            raise ServiceConfigError(
                f"--remote lists endpoint {target!r} more than once")
        seen.add(target)
        targets.append(target)
    return targets


def _validate_target(target: str) -> None:
    if target.startswith("unix:"):
        if len(target) == len("unix:"):
            raise ServiceConfigError(
                f"malformed --remote endpoint {target!r}: empty unix "
                "socket path")
        return
    host, sep, port = target.rpartition(":")
    if not sep or not host:
        raise ServiceConfigError(
            f"malformed --remote endpoint {target!r} (want HOST:PORT "
            "or unix:/path.sock)")
    if not port.isdigit() or not 0 < int(port) < 65536:
        raise ServiceConfigError(
            f"malformed --remote endpoint {target!r}: bad port {port!r}")


def pattern_fingerprint(patterns: Sequence[str],
                        exclude: "Sequence[str] | None" = None,
                        ignore_case: bool = False) -> str:
    """Content fingerprint of a compiled pattern set — the hash-mode
    routing key. Two collectors invoked with the same --match/--exclude
    set (order-sensitive, like the Hello handshake) land on the same
    shard owner."""
    h = hashlib.sha256()
    for p in patterns:
        h.update(b"m\x00" + p.encode() + b"\x00")
    for p in exclude or ():
        h.update(b"x\x00" + p.encode() + b"\x00")
    h.update(b"i" if ignore_case else b"c")
    return h.hexdigest()[:16]


class _Endpoint:
    """One fleet member: the wrapped client plus the router's view of
    its health (prober-observed readiness; the breaker lives on the
    client)."""

    __slots__ = ("target", "client", "ready", "readyz", "verified",
                 "quarantined", "cap_offered", "cap_admitted", "cap_next",
                 "weight", "cap_at", "wrr")

    def __init__(self, target: str, client: Any) -> None:
        self.target = target
        self.client = client
        # Unknown = routable: a fleet with no metrics ports configured
        # must still route everywhere (breakers alone protect it).
        self.ready = True
        self.readyz: "tuple[str, int] | None" = None
        # Last capacity totals this endpoint's Hello advertised (the
        # collector-side counter re-export advances by deltas) and
        # when the prober should refresh them next.
        self.cap_offered: "int | None" = None
        self.cap_admitted: "int | None" = None
        self.cap_next = 0.0
        # Capacity-weighted routing state: the raw headroom-derived
        # weight, when it was advertised (None = never — weight stays
        # uniform), and the smooth-WRR accumulator.
        self.weight = 1.0
        self.cap_at: "float | None" = None
        self.wrr = 0.0
        # verified False = the endpoint was unreachable during the
        # startup handshake: it must not receive traffic until a later
        # Hello proves its pattern set matches (the prober re-tries).
        # quarantined = it came back with a DRIFTED set: permanently
        # excluded — mis-filtered output is worse than less capacity.
        self.verified = True
        self.quarantined = False

    @property
    def breaker(self) -> CircuitBreaker:
        return self.client.breaker


class ShardedFilterClient:
    """N ``RemoteFilterClient``s behind the one-client API.

    ``client_factory`` (tests) builds the per-endpoint client; the
    default builds a ``RemoteFilterClient`` with ``client_kwargs``
    (TLS/auth/timeout config shared across the fleet) and a
    per-endpoint breaker named ``rpc@<target>``.
    """

    def __init__(self, targets: Iterable[str], *,
                 shard_mode: str = "round-robin",
                 fingerprint: str = "",
                 hedge_s: "float | None" = DEFAULT_HEDGE_S,
                 probe_interval_s: float = DEFAULT_PROBE_INTERVAL_S,
                 probe_timeout_s: float = DEFAULT_PROBE_TIMEOUT_S,
                 registry: Any = None,
                 client_factory: "Callable[[str], Any] | None" = None,
                 resolver: "Resolver | None" = None,
                 **client_kwargs: Any) -> None:
        if shard_mode not in SHARD_MODES:
            raise ServiceConfigError(
                f"unknown --shard-mode {shard_mode!r} "
                f"(want {' | '.join(SHARD_MODES)})")
        target_list = list(targets)
        if not target_list and resolver is None:
            # With a resolver, an empty seed list is legal: the first
            # membership fill happens in verify_patterns, inside the
            # running loop, from the resolver's own snapshot.
            raise ServiceConfigError("--remote endpoint list is empty")
        seen: set[str] = set()
        for t in target_list:
            # Same wording as parse_endpoints (which guards the CLI
            # path); re-checked here for direct library construction.
            if t in seen:
                raise ServiceConfigError(
                    f"--remote lists endpoint {t!r} more than once")
            seen.add(t)
        if client_factory is None:
            def client_factory(target: str) -> Any:
                return RemoteFilterClient(target, registry=registry,
                                          **client_kwargs)
        self._client_factory = client_factory
        self._mode = shard_mode
        self._fingerprint = fingerprint
        # The collector's pattern-set invocation, remembered by
        # verify_patterns so an endpoint that was down at startup can
        # be verified when it comes back (see _late_verify).
        self._expected: "tuple[list[str], bool, list[str]] | None" = None
        self._hedge_s = hedge_s
        self._probe_interval_s = probe_interval_s
        self._probe_timeout_s = probe_timeout_s
        self._registry = registry
        self._endpoints = [_Endpoint(t, client_factory(t))
                           for t in target_list]
        self._rr = 0  # round-robin cursor (per-batch rotation)
        # Hash mode: endpoints and fingerprint are fixed for the life
        # of the client, so the ring walk is a constant — computed once
        # here, not per batch (demotion/exclusion happens later, in
        # _route_order, against live health state).
        self._hash_order: "list[int]" = (
            self._ring_walk() if shard_mode == "hash" else [])
        self._probe_task: "asyncio.Task | None" = None
        # Created lazily inside the running loop (_ensure_prober): an
        # Event constructed here would bind/require the thread's event
        # loop on older Pythons, and this constructor legitimately runs
        # before any loop exists (make_pipeline at CLI startup, tests).
        self._probe_stop: "asyncio.Event | None" = None
        self._m_hedges: Any = None
        self._m_reroutes: Any = None
        self._m_batches: Any = None
        self._m_ready: Any = None
        self._m_cap_head: Any = None
        self._m_cap_off: Any = None
        self._m_cap_adm: Any = None
        self._m_weight: Any = None
        self._m_member_events: Any = None
        self._m_member_size: Any = None
        from klogs_tpu.utils.env import nonneg_float, positive_float

        self._cap_refresh_s = positive_float(
            "KLOGS_FLEET_REFRESH_S", DEFAULT_CAPACITY_REFRESH_S,
            exc=ServiceConfigError)
        # Validated at construction (startup), not first use inside the
        # prober task — a malformed knob must fail naming itself, not
        # silently kill background routing mid-run.
        try:
            self._weight_decay_s = nonneg_float(
                "KLOGS_WEIGHT_DECAY_S", DEFAULT_WEIGHT_DECAY_S)
        except ValueError as e:
            raise ServiceConfigError(str(e)) from None
        # Live membership (service/resolver.py): polled by the prober
        # on its own cadence; 0.0 forces a poll on the first cycle.
        self._resolver = resolver
        self._resolver_next = 0.0
        self._resolver_interval_s = 0.0
        if resolver is not None:
            from klogs_tpu.service.resolver import (
                DEFAULT_RESOLVE_INTERVAL_S,
            )

            self._resolver_interval_s = positive_float(
                "KLOGS_RESOLVER_INTERVAL_S", DEFAULT_RESOLVE_INTERVAL_S,
                exc=ServiceConfigError)
        # Bumped on every membership change; _dispatch snapshots it and
        # re-routes when it moves mid-batch (the ring-generation guard).
        self._ring_gen = 0
        # Retired endpoints' channel-close tasks: strong refs so they
        # cannot be GC'd mid-close, settled in aclose.
        self._member_tasks: "set[asyncio.Task]" = set()
        if registry is not None:
            self._m_hedges = registry.family("klogs_shard_hedges_total")
            self._m_reroutes = registry.family("klogs_shard_reroutes_total")
            self._m_batches = registry.family("klogs_shard_batches_total")
            self._m_ready = registry.family("klogs_shard_endpoint_ready")
            self._m_cap_head = registry.family(
                "klogs_fleet_endpoint_headroom")
            self._m_cap_off = registry.family(
                "klogs_fleet_endpoint_offered_lines_total")
            self._m_cap_adm = registry.family(
                "klogs_fleet_endpoint_admitted_lines_total")
            self._m_weight = registry.family("klogs_shard_endpoint_weight")
            self._m_member_events = registry.family(
                "klogs_fleet_membership_events_total")
            self._m_member_size = registry.family(
                "klogs_fleet_membership_size")
            self._m_member_size.set(len(self._endpoints))
            for ep in self._endpoints:
                self._m_ready.labels(endpoint=ep.target).set(1)

    # -- routing ------------------------------------------------------

    def _ring_walk(self) -> "list[int]":
        """Endpoint indices in consistent-hash order for this client's
        fingerprint: the ring (vnodes per endpoint) walked clockwise
        from the fingerprint's position, first occurrence of each
        endpoint kept."""
        ring: "list[tuple[int, int]]" = []
        for i, ep in enumerate(self._endpoints):
            for v in range(_RING_VNODES):
                digest = hashlib.sha256(
                    f"{ep.target}#{v}".encode()).digest()
                ring.append((int.from_bytes(digest[:8], "big"), i))
        ring.sort()
        key = int.from_bytes(hashlib.sha256(
            self._fingerprint.encode()).digest()[:8], "big")
        start = bisect.bisect_left(ring, (key, -1))
        order: "list[int]" = []
        seen: set[int] = set()
        for j in range(len(ring)):
            _, i = ring[(start + j) % len(ring)]
            if i not in seen:
                seen.add(i)
                order.append(i)
                if len(order) == len(self._endpoints):
                    break
        return order

    def _natural_order(self) -> "list[_Endpoint]":
        """Health-blind candidate order: the pure routing policy."""
        if not self._endpoints:
            # Legal mid-run with a resolver: the fleet can shrink to
            # zero between polls (every dispatch then raises
            # Unavailable until membership recovers).
            return []
        if self._mode == "hash":
            return [self._endpoints[i] for i in self._hash_order]
        i = self._rr % len(self._endpoints)
        self._rr += 1
        return self._endpoints[i:] + self._endpoints[:i]

    def _effective_weight(self, ep: _Endpoint, now: float) -> float:
        """Headroom-learned weight decayed toward uniform 1.0 as the
        last capacity sample ages: a silent prober (endpoint stopped
        answering Hello, so ``cap_at`` froze) loses its learned bias
        within ``KLOGS_WEIGHT_DECAY_S`` instead of starving — or
        forever favoring — anyone."""
        if self._weight_decay_s <= 0 or ep.cap_at is None:
            return 1.0
        fresh = max(0.0, 1.0 - (now - ep.cap_at) / self._weight_decay_s)
        return 1.0 + fresh * (ep.weight - 1.0)

    def _weighted_order(self,
                        healthy: "list[_Endpoint]"
                        ) -> "list[_Endpoint] | None":
        """Smooth weighted round-robin over the healthy set (nginx
        algorithm: deterministic, no starvation — every endpoint is
        visited, just proportionally less often). Returns None when
        weighting is disabled or the weights are effectively uniform,
        so the caller keeps today's rotation byte-identically."""
        if self._weight_decay_s <= 0:
            return None
        now = time.monotonic()
        weights = [self._effective_weight(ep, now) for ep in healthy]
        if max(weights) - min(weights) < 1e-6:
            return None
        total = 0.0
        for ep, w in zip(healthy, weights):
            ep.wrr += w
            total += w
        order = sorted(healthy, key=lambda ep: -ep.wrr)
        order[0].wrr -= total
        return order

    def _route_order(self) -> "list[_Endpoint]":
        """Candidate order for one batch: available endpoints first (in
        policy order), the unready/broken ones demoted to last resort —
        tried only after every healthy sibling failed, which is what
        makes --on-filter-error degrade fire only when the WHOLE fleet
        is down. Skipping the natural owner is counted per endpoint and
        reason. Unverified/quarantined endpoints are EXCLUDED, not
        demoted: a server whose pattern set was never (or wrongly)
        verified would silently mis-filter — worse than losing its
        capacity."""
        natural = [ep for ep in self._natural_order()
                   if ep.verified and not ep.quarantined]
        if not natural:
            return []
        # One health snapshot per routing decision (breaker.state can
        # flip open->half-open on the clock mid-iteration) — the
        # reroute reason derives from the SAME snapshot, or the label
        # could misattribute a breaker trip to readiness drain.
        state = {ep.target: ep.breaker.state for ep in natural}
        avail = {ep.target: (ep.ready
                             and state[ep.target] != BREAKER_OPEN)
                 for ep in natural}
        healthy = [ep for ep in natural if avail[ep.target]]
        if not healthy:
            return natural
        for ep in natural:
            if ep is healthy[0]:
                break
            reason = ("breaker" if state[ep.target] == BREAKER_OPEN
                      else "unready")
            if self._m_reroutes is not None:
                self._m_reroutes.labels(endpoint=ep.target,
                                        reason=reason).inc()
            # The batch trace records WHICH owner was skipped and why —
            # the per-batch story the aggregate counter cannot tell.
            trace.TRACER.event("shard.reroute", endpoint=ep.target,
                               reason=reason)
        # Capacity weighting reorders WITHIN the healthy set only, and
        # only in round-robin mode (hash mode pins ownership — skipping
        # the ring owner for capacity would churn key placement). It
        # runs AFTER the reroute accounting above: weighting is policy,
        # not a health event.
        if self._mode == "round-robin" and len(healthy) > 1:
            weighted = self._weighted_order(healthy)
            if weighted is not None:
                healthy = weighted
        return healthy + [ep for ep in natural if not avail[ep.target]]

    def _note_endpoint_down(self, ep: _Endpoint) -> None:
        """A dispatch just failed terminally at ``ep``. If its breaker
        has opened, the server is down — and downtime is the redeploy
        window: whatever comes back on that address may serve a
        DIFFERENT pattern set. Demote it to unverified so the prober
        must re-run the handshake before it gets another batch (only
        meaningful when verify_patterns armed the expected config)."""
        if (self._expected is not None and ep.verified
                and ep.breaker.state == BREAKER_OPEN):
            ep.verified = False
            if self._m_ready is not None:
                self._m_ready.labels(endpoint=ep.target).set(0)
            self._ensure_prober()

    # -- live membership ----------------------------------------------

    def _member_event(self, action: str) -> None:
        if self._m_member_events is not None:
            self._m_member_events.labels(action=action).inc()

    async def apply_membership(self, targets: "Iterable[str]"
                               ) -> "tuple[list[str], list[str]]":
        """Diff a resolver snapshot against live membership and apply
        it: joiners enter the fleet UNVERIFIED (the prober's
        verify-before-rejoin handshake gates their first batch, unless
        no expected config is armed yet), leavers have their channels
        retired in the background and their per-endpoint series
        dropped. Any change bumps the ring generation so an in-flight
        dispatch re-routes. Returns (added, removed) target lists."""
        valid: "list[str]" = []
        seen: "set[str]" = set()
        for raw in targets:
            t = raw.strip()
            if not t or t in seen:
                continue
            try:
                _validate_target(t)
            except ServiceConfigError as e:
                # One bad record must not poison the snapshot: keep
                # the good entries, skip (and count) the bad one.
                self._member_event("error")
                term.warning("resolver returned a malformed endpoint "
                             "%r (%s); skipping it", t, e)
                continue
            seen.add(t)
            valid.append(t)
        if not valid and self._endpoints:
            # Refuse to drain the whole fleet on a (possibly bogus)
            # empty snapshot — a half-deployed Endpoints object or a
            # truncated file must not stop a flowing pipeline. Scale-
            # to-zero on purpose is a restart-sized decision anyway.
            self._member_event("error")
            term.warning(
                "resolver returned an EMPTY endpoint set; keeping the "
                "current fleet of %d", len(self._endpoints))
            return [], []
        current = {ep.target for ep in self._endpoints}
        added = [t for t in valid if t not in current]
        removed = [t for t in current if t not in seen]
        if not added and not removed:
            return [], []
        keep = [ep for ep in self._endpoints if ep.target in seen]
        leavers = [ep for ep in self._endpoints if ep.target not in seen]
        for t in added:
            ep = _Endpoint(t, self._client_factory(t))
            # Pre-handshake joins (resolver seeding before
            # verify_patterns) are verified by the imminent handshake
            # itself; post-handshake joiners wait for the prober.
            ep.verified = self._expected is None
            keep.append(ep)
            self._member_event("add")
            if self._m_ready is not None:
                self._m_ready.labels(endpoint=ep.target).set(
                    1 if ep.verified else 0)
            term.info("filterd %s joined the fleet%s", t,
                      "" if ep.verified
                      else " (unverified until its pattern set checks)")
        self._endpoints = keep
        for ep in leavers:
            self._member_event("remove")
            await self._retire(ep)
            term.info("filterd %s left the fleet", ep.target)
        self._ring_gen += 1
        if self._mode == "hash":
            self._hash_order = self._ring_walk()
        if self._m_member_size is not None:
            self._m_member_size.set(len(self._endpoints))
        self._ensure_prober()
        return added, removed

    async def _retire(self, ep: _Endpoint) -> None:
        """Close a leaver's channel off the hot path and drop its
        per-endpoint series (a scrape must not keep exporting a gauge
        for an endpoint that no longer exists)."""
        for fam in (self._m_ready, self._m_cap_head, self._m_cap_off,
                    self._m_cap_adm, self._m_weight):
            if fam is not None:
                fam.remove(endpoint=ep.target)

        async def _close(client: Any = ep.client) -> None:
            try:
                await client.aclose()
            except Exception:  # noqa: BLE001
                pass  # retirement teardown; the channel is gone either way

        task = asyncio.get_running_loop().create_task(_close())
        self._member_tasks.add(task)
        task.add_done_callback(self._member_tasks.discard)

    async def _resolve_step(self) -> None:
        """One membership poll: ask the resolver for the current fleet
        and apply the diff. Every failure mode keeps the current
        membership — discovery is advisory, never load-bearing."""
        self._resolver_next = (time.monotonic()
                               + self._resolver_interval_s)
        assert self._resolver is not None
        try:
            targets = await self._resolver.resolve()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            # ResolverError, InjectedFault, or a resolver bug: all
            # transient from membership's point of view.
            self._member_event("error")
            term.warning("endpoint resolver %s failed (%s); keeping the "
                         "current fleet of %d",
                         self._resolver.describe(), e,
                         len(self._endpoints))
            return
        await self.apply_membership(targets)

    # -- dispatch -----------------------------------------------------

    async def _dispatch(self,
                        op: "Callable[[Any], Awaitable[Any]]",
                        what: str) -> Any:
        """Run one batch against the fleet: primary attempt, a hedge
        against the next sibling every ``hedge_s`` of silence, failover
        past terminal failures, first success wins. Losers are
        cancelled and awaited before returning — no orphan tasks, no
        double-counted result.

        The whole decision runs under one ``shard.dispatch`` span;
        routing demotions, hedges, per-endpoint failures, and the
        winner land on it as events, and each attempt task inherits the
        span as parent (its ``rpc.client`` span nests under it; a
        cancelled loser's closes status=cancelled)."""
        with trace.TRACER.span("shard.dispatch", what=what,
                               mode=self._mode) as sp:
            queue = list(self._route_order())
            gen = self._ring_gen
            tasks: "dict[asyncio.Task, _Endpoint]" = {}
            errors: "list[str]" = []
            pending: "set[asyncio.Task]" = set()
            try:
                while queue or pending:
                    if self._ring_gen != gen:
                        # Membership changed mid-batch: the queued
                        # candidates may include retired endpoints (or
                        # miss fresh ones). Re-route from the current
                        # ring, keeping attempts already in flight.
                        gen = self._ring_gen
                        attempted = {tasks[t].target for t in tasks}
                        queue = [ep for ep in self._route_order()
                                 if ep.target not in attempted]
                        if not queue and not pending:
                            break  # refresh drained the candidates
                    if not pending:
                        ep = queue.pop(0)
                        sp.add_event("shard.route", endpoint=ep.target)
                        t = asyncio.ensure_future(op(ep.client))
                        tasks[t] = ep
                        pending = {t}
                    timeout = (self._hedge_s
                               if queue and self._hedge_s is not None
                               else None)
                    done, pending = await asyncio.wait(
                        pending, timeout=timeout,
                        return_when=asyncio.FIRST_COMPLETED)
                    if not done:
                        # Hedge deadline passed with the attempt(s)
                        # still in flight: race one more sibling.
                        ep = queue.pop(0)
                        if self._m_hedges is not None:
                            self._m_hedges.labels(endpoint=ep.target).inc()
                        sp.add_event("shard.hedge", endpoint=ep.target)
                        t = asyncio.ensure_future(op(ep.client))
                        tasks[t] = ep
                        pending.add(t)
                        continue
                    winner: "asyncio.Task | None" = None
                    fatal: "BaseException | None" = None
                    for t in done:
                        exc = t.exception() if not t.cancelled() else None
                        if t.cancelled():
                            continue
                        if exc is None:
                            winner = winner or t
                        elif isinstance(exc, Unavailable):
                            ep = tasks[t]
                            errors.append(f"{ep.target}: {exc}")
                            reason = ("breaker"
                                      if isinstance(exc, BreakerOpen)
                                      else "error")
                            if self._m_reroutes is not None:
                                self._m_reroutes.labels(
                                    endpoint=ep.target, reason=reason).inc()
                            sp.add_event("shard.failover",
                                         endpoint=ep.target, reason=reason,
                                         error=str(exc))
                            self._note_endpoint_down(ep)
                        else:
                            # Non-transient (pattern mismatch, bad
                            # request, auth): the same bug on every
                            # endpoint — propagate, do not failover.
                            fatal = fatal or exc
                    if winner is not None:
                        # A valid verdict beats a loser's error — even a
                        # non-transient one (a hedge sibling's pattern
                        # mismatch / auth failure is per-endpoint in a
                        # heterogeneous fleet; the next dispatch routed
                        # to it will surface it on its own).
                        if self._m_batches is not None:
                            self._m_batches.labels(
                                endpoint=tasks[winner].target).inc()
                        sp.set_attr("winner", tasks[winner].target)
                        return await winner  # done: resolves immediately
                    if fatal is not None:
                        raise fatal
                raise Unavailable(
                    f"all {len(self._endpoints)} filterd endpoint(s) "
                    f"unavailable for {what}: "
                    + ("; ".join(errors)
                       or "no routable endpoint (unverified or "
                          "quarantined pattern sets)"))
            finally:
                live = [t for t in tasks if not t.done()]
                for t in live:
                    t.cancel()
                for t in live:
                    try:
                        await t
                    except (asyncio.CancelledError, Exception):  # noqa: BLE001
                        pass  # loser teardown; its outcome is irrelevant

    # -- client API ---------------------------------------------------

    async def hello(self) -> dict:
        return await self._dispatch(lambda c: c.hello(), "hello")

    async def match(self, lines: "list[bytes]") -> "list[bool]":
        result = await self._dispatch(lambda c: c.match(lines), "match")
        return result

    async def match_framed(self, payload: bytes, offsets: Any) -> Any:
        return await self._dispatch(
            lambda c: c.match_framed(payload, offsets), "match_framed")

    async def verify_patterns(self, patterns: "list[str]",
                              ignore_case: bool = False,
                              exclude: "list[str] | None" = None) -> None:
        """Startup handshake against EVERY endpoint: any reachable
        server with a drifted pattern set fails the run (a mismatched
        shard would silently mis-filter every batch routed to it); an
        unreachable server is warned about, excluded from routing, and
        re-verified by the background prober when it comes back — a
        partial fleet must not block startup, surviving one is the
        point of this tier. All-down is a hard error. Hello responses
        also teach the prober where each endpoint's /readyz lives."""
        if self._resolver is not None and not self._endpoints:
            # Resolver-seeded fleet (no --remote list): the FIRST
            # membership fill must succeed — there is nothing to keep
            # flying on. Applied before _expected is armed, so these
            # seeds are verified by the handshake below, exactly like
            # a static list.
            try:
                targets = await self._resolver.resolve()
            except Exception as e:  # noqa: BLE001
                raise Unavailable(
                    f"endpoint resolver {self._resolver.describe()} "
                    f"failed at startup: {e}") from e
            await self.apply_membership(targets)
            if not self._endpoints:
                raise Unavailable(
                    f"endpoint resolver {self._resolver.describe()} "
                    "returned no endpoints at startup")
        self._expected = (list(patterns), bool(ignore_case),
                          list(exclude or []))
        # Concurrent: each hello still gets its client's full retry
        # budget (a startup blip deserves patience), but the fleet pays
        # the MAX of the towers, not the sum — one black-holing node
        # costs what a single-endpoint setup would, never minutes per
        # dead endpoint.
        infos = await asyncio.gather(
            *[ep.client.hello() for ep in self._endpoints],
            return_exceptions=True)
        down: "list[str]" = []
        reachable = 0
        to_register: "list[_Endpoint]" = []
        for ep, info in zip(self._endpoints, infos):
            if isinstance(info, Unavailable):
                down.append(f"{ep.target}: {info}")
                ep.verified = False
                if self._m_ready is not None:
                    # The gauge promises "0 = draining or unreachable";
                    # an endpoint excluded from routing must not scrape
                    # as ready.
                    self._m_ready.labels(endpoint=ep.target).set(0)
                term.warning(
                    "filterd %s unavailable at startup (%s); continuing "
                    "with the rest of the fleet (it rejoins once its "
                    "pattern set verifies)", ep.target, info)
                continue
            if isinstance(info, BaseException):
                # Non-transient (config/auth bug): the run cannot
                # sensibly start — propagate the first one.
                raise info
            reachable += 1
            if check_server_config(ep.target, info, patterns, ignore_case,
                                   exclude) == "register":
                # Multi-tenant registry endpoint: this collector's set
                # must be registered there before the first batch.
                to_register.append(ep)
            self._learn_readyz(ep, info)
            self._note_capacity(ep, info)
        if not reachable:
            raise Unavailable(
                "no filterd endpoint reachable at startup: "
                + "; ".join(down))
        if to_register:
            # Concurrent like the hellos: each endpoint pays its own
            # compile (content-addressed: usually a reuse), the fleet
            # pays the MAX, not the sum. An endpoint that died between
            # Hello and Register gets the same treatment as one down at
            # Hello — excluded until the prober late-verifies it; only
            # a non-transient failure (the collector's own set failing
            # to compile) aborts startup.
            results = await asyncio.gather(
                *[ep.client.ensure_registered(patterns, ignore_case,
                                              exclude=exclude)
                  for ep in to_register],
                return_exceptions=True)
            for ep, res in zip(to_register, results):
                if isinstance(res, Unavailable):
                    ep.verified = False
                    if self._m_ready is not None:
                        self._m_ready.labels(endpoint=ep.target).set(0)
                    term.warning(
                        "filterd %s went away before registration "
                        "completed (%s); continuing with the rest of "
                        "the fleet", ep.target, res)
                elif isinstance(res, BaseException):
                    raise res
        self._ensure_prober()

    async def refresh_capacity(self) -> None:
        """One fleet-wide capacity sweep (concurrent, bounded per
        endpoint): refresh every routable endpoint's klogs_fleet_
        endpoint_* series from a Hello NOW. The prober does this on
        its own cadence for long-lived runs; a short batch run calls
        it before its --stats-json exit dump so the fleet's
        offered/admitted totals still land — the last scrape."""
        if self._m_cap_head is None or self._expected is None:
            return
        await asyncio.gather(
            *[self._refresh_capacity(ep) for ep in self._endpoints
              if ep.verified and not ep.quarantined
              and ep.breaker.state != BREAKER_OPEN],
            return_exceptions=True)

    async def aclose(self) -> None:
        if self._probe_stop is not None:
            self._probe_stop.set()
        if self._probe_task is not None:
            self._probe_task.cancel()
            try:
                await self._probe_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                # A prober that died on its own error must not abort
                # teardown — the per-endpoint channels still need
                # closing below.
                pass
            self._probe_task = None
        if self._member_tasks:
            # Retired-channel closes still in flight: settle them so no
            # task outlives the client (task_lifecycle discipline).
            await asyncio.gather(*list(self._member_tasks),
                                 return_exceptions=True)
            self._member_tasks.clear()
        if self._resolver is not None:
            try:
                await self._resolver.aclose()
            except Exception:  # noqa: BLE001
                pass  # discovery teardown must not mask pipeline close
        await asyncio.gather(
            *[ep.client.aclose() for ep in self._endpoints],
            return_exceptions=True)

    def close(self) -> None:
        if self._probe_stop is not None:
            self._probe_stop.set()
        if self._probe_task is not None:
            self._probe_task.cancel()
            self._probe_task = None
        for ep in self._endpoints:
            ep.client.close()

    # -- fleet capacity re-export -------------------------------------

    def _note_capacity(self, ep: _Endpoint, info: dict) -> None:
        """Fold one Hello's capacity advertisement into the per-
        endpoint klogs_fleet_endpoint_* families: headroom is a gauge
        (last advertised value), offered/admitted are counters
        advanced by the observed delta — a restarted server (total
        dropped) restarts its contribution from the new total rather
        than poisoning the series with a negative increment."""
        ep.cap_next = time.monotonic() + self._cap_refresh_s
        head = info.get("headroom")
        if isinstance(head, (int, float)) and not isinstance(head, bool):
            # Routing weight learns from every Hello (registry or not):
            # clamp to [0,1], then floor — a saturated endpoint still
            # gets a trickle (its Hello is how it advertises recovery).
            ep.weight = max(_WEIGHT_FLOOR, min(1.0, max(0.0, float(head))))
            ep.cap_at = time.monotonic()
        if self._m_cap_head is None:
            return
        if isinstance(head, (int, float)) and not isinstance(head, bool):
            self._m_cap_head.labels(endpoint=ep.target).set(float(head))
        for key, attr, fam in (
                ("fleet_offered_lines", "cap_offered", self._m_cap_off),
                ("fleet_admitted_lines", "cap_admitted", self._m_cap_adm)):
            total = info.get(key)
            if not isinstance(total, int) or isinstance(total, bool):
                continue
            last: "int | None" = getattr(ep, attr)
            if last is not None and last // 2 < total < last:
                # STALE, not a restart: two concurrent Hellos (prober
                # cadence racing the exit-dump sweep) can land out of
                # order, and re-counting a lifetime total as a fresh
                # delta would spike the HPA's shed-pressure rate by
                # the endpoint's whole history in one scrape. A real
                # restart collapses the total towards zero; a slightly
                # smaller total is the older in-flight answer — keep
                # the newer state.
                continue
            delta = total - last if (last is not None
                                     and total >= last) else total
            if delta > 0:
                fam.labels(endpoint=ep.target).inc(delta)
            setattr(ep, attr, total)

    async def _refresh_capacity(self, ep: _Endpoint) -> None:
        """Prober-cadence capacity refresh: one bounded Hello against a
        verified, breaker-closed endpoint. Still-down endpoints simply
        wait for the next cycle (their gauges keep the last advertised
        value; routing state is the prober's other jobs' concern)."""
        try:
            info = await asyncio.wait_for(ep.client.hello(),
                                          timeout=self._probe_timeout_s)
        except (Unavailable, asyncio.TimeoutError):
            ep.cap_next = time.monotonic() + self._cap_refresh_s
            return
        self._note_capacity(ep, info)

    # -- readiness drain ----------------------------------------------

    _LOOPBACK = frozenset({"127.0.0.1", "localhost", "::1"})

    def _learn_readyz(self, ep: _Endpoint, info: dict) -> None:
        port = info.get("metrics_port")
        if not port or ep.target.startswith("unix:"):
            return  # no sidecar advertised: breakers alone guard it
        grpc_host = ep.target.rpartition(":")[0]
        if grpc_host.startswith("[") and grpc_host.endswith("]"):
            grpc_host = grpc_host[1:-1]
        # Where is the advertised sidecar actually reachable? Older
        # servers omit metrics_host; assume the conservative loopback
        # default they ship with.
        mhost = str(info.get("metrics_host") or "127.0.0.1")
        if mhost in ("0.0.0.0", "::"):
            host = grpc_host  # wildcard bind: same host as the RPCs
        elif mhost in self._LOOPBACK:
            if grpc_host not in self._LOOPBACK:
                # Loopback-bound sidecar on a REMOTE node: probing
                # grpc_host:port would hit nothing (or a stranger) and
                # a refused probe would wrongly demote a healthy
                # server. Skip — breakers alone guard this endpoint.
                return
            host = grpc_host
        else:
            host = mhost  # explicit routable bind address
        ep.readyz = (host, int(port))

    def _ensure_prober(self) -> None:
        if (self._probe_task is None
                and (any(ep.readyz for ep in self._endpoints)
                     or any(not ep.verified for ep in self._endpoints)
                     or self._m_cap_head is not None
                     or self._resolver is not None)):
            if self._probe_stop is None:
                self._probe_stop = asyncio.Event()
            self._probe_task = asyncio.get_running_loop().create_task(
                self._probe_loop())

    def _set_ready(self, ep: _Endpoint, ready: bool) -> None:
        if ready != ep.ready:
            if ready:
                term.info("filterd %s is ready again; rejoining the "
                          "rotation", ep.target)
            else:
                term.warning("filterd %s is draining (/readyz not ok); "
                             "routing around it", ep.target)
        ep.ready = ready
        if self._m_ready is not None:
            self._m_ready.labels(endpoint=ep.target).set(1 if ready else 0)

    async def _probe_loop(self) -> None:
        """Poll each endpoint's /readyz on a fixed cadence, and retry
        the startup handshake for endpoints that were down when
        verify_patterns ran. Not a retry loop in the policy sense:
        outcomes only flip routing state, and the wait is the
        stop-aware poller idiom (wait_for on the stop event), so a
        teardown mid-interval returns immediately."""
        stop = self._probe_stop
        assert stop is not None  # created by _ensure_prober
        while not stop.is_set():
            if (self._resolver is not None
                    and time.monotonic() >= self._resolver_next):
                # Membership poll rides the prober cadence but keeps
                # its own (usually longer) interval.
                try:
                    await self._resolve_step()
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    # _resolve_step already swallows resolver failures;
                    # this guards apply_membership itself — a bug there
                    # must not kill drain/late-verify for the fleet.
                    term.warning("membership update failed: %s", e)
            for ep in list(self._endpoints):
                if stop.is_set() or ep.quarantined:
                    continue
                if self._m_weight is not None:
                    # Exported weight is the EFFECTIVE one (decay
                    # applied) — what routing actually uses right now.
                    self._m_weight.labels(endpoint=ep.target).set(
                        self._effective_weight(ep, time.monotonic()))
                try:
                    if not ep.verified:
                        await self._late_verify(ep)
                    elif ep.readyz is not None:
                        self._set_ready(ep, await self._probe_ready(ep))
                    if (self._m_cap_head is not None
                            and self._expected is not None
                            and ep.verified
                            and ep.breaker.state != BREAKER_OPEN
                            and time.monotonic() >= ep.cap_next):
                        # Capacity re-export cadence: refresh this
                        # endpoint's headroom/offered/admitted gauges
                        # from a bounded Hello (KLOGS_FLEET_REFRESH_S).
                        await self._refresh_capacity(ep)
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001
                    # A surprise here (e.g. a deleted token file turning
                    # hello into ServiceConfigError) must not kill the
                    # prober silently — drain and late-verify for the
                    # whole fleet would stop for the rest of the run.
                    term.warning("filterd %s health probe failed: %s",
                                 ep.target, e)
            try:
                await asyncio.wait_for(stop.wait(),
                                       timeout=self._probe_interval_s)
            except asyncio.TimeoutError:
                pass

    async def _late_verify(self, ep: _Endpoint) -> None:
        """An endpoint that was down during verify_patterns came (or
        may have come) back: verify its pattern set before it gets a
        single batch. Matching set -> it joins the rotation (and its
        /readyz is learned); a DRIFTED set -> permanent quarantine with
        one loud error — a redeployed shard serving different patterns
        must never silently mis-filter its share of the stream."""
        assert self._expected is not None  # set by verify_patterns
        patterns, ignore_case, exclude = self._expected
        try:
            # Bounded, no patience: the inner client's full retry tower
            # (minutes against a black-holing node) would stall this
            # sequential probe loop — and with it /readyz drain for
            # every HEALTHY sibling. A handshake that cannot answer
            # within the probe budget is simply still down.
            info = await asyncio.wait_for(ep.client.hello(),
                                          timeout=self._probe_timeout_s)
        except (Unavailable, asyncio.TimeoutError):
            return  # still down; try again next probe cycle
        try:
            status = check_server_config(ep.target, info, patterns,
                                         ignore_case, exclude)
        except PatternMismatch as e:
            ep.quarantined = True
            if self._m_ready is not None:
                self._m_ready.labels(endpoint=ep.target).set(0)
            term.error(
                "filterd %s came back with a DRIFTED pattern set; "
                "quarantining it for the rest of the run (%s)",
                ep.target, e)
            return
        if status == "register":
            # A multi-set endpoint that restarted lost our
            # registration: re-register before routing to it. Bounded,
            # but with a compile-sized floor — a fresh registration IS
            # a compile, unlike the instant Hello above; a node that
            # cannot finish within the budget simply stays out until
            # the next cycle (registration is idempotent server-side).
            try:
                await asyncio.wait_for(
                    ep.client.ensure_registered(patterns, ignore_case,
                                                exclude=exclude),
                    timeout=max(self._probe_timeout_s, 10.0))
            except (Unavailable, asyncio.TimeoutError):
                return
        ep.verified = True
        if self._m_ready is not None:
            self._m_ready.labels(endpoint=ep.target).set(1 if ep.ready
                                                         else 0)
        self._learn_readyz(ep, info)
        self._note_capacity(ep, info)
        term.info("filterd %s verified; joining the rotation", ep.target)

    async def _probe_ready(self, ep: _Endpoint) -> bool:
        """One GET /readyz. 200 = ready; a 503 (draining/cold), refused
        connection, or timeout all mean 'do not route here' — exactly
        the kubelet's readiness semantics."""
        assert ep.readyz is not None
        host, port = ep.readyz
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port),
                self._probe_timeout_s)
        except (OSError, asyncio.TimeoutError):
            return False
        try:
            writer.write(b"GET /readyz HTTP/1.1\r\nHost: " +
                         host.encode() + b"\r\nConnection: close\r\n\r\n")
            await asyncio.wait_for(writer.drain(), self._probe_timeout_s)
            status = await asyncio.wait_for(reader.readline(),
                                            self._probe_timeout_s)
            parts = status.split()
            return len(parts) >= 2 and parts[1] == b"200"
        except (OSError, asyncio.TimeoutError):
            return False
        finally:
            writer.close()
            try:
                # Bounded: an unanswered close handshake would wedge
                # the prober coroutine forever mid-probe, freezing
                # drain detection for the WHOLE fleet (observed as a
                # rare suite-order hang; kubelet probes are bounded
                # end to end for the same reason).
                await asyncio.wait_for(writer.wait_closed(),
                                       self._probe_timeout_s)
            except (OSError, asyncio.TimeoutError):
                pass
