"""Build version stamping.

Reference parity: klogs stamps ``cmd.BuildVersion`` at link time via
``-ldflags -X ...cmd.BuildVersion=<tag>`` (cmd/root.go:31-33,
.github/workflows/release.yaml:65) and defaults to "development".
The Python analog is an environment override at import time.
"""

from klogs_tpu.utils.env import read as _env_read

BUILD_VERSION = _env_read("KLOGS_BUILD_VERSION", "development")
