"""North-star benchmark: log lines/sec filtered, 32 patterns x 256-pod
batches, TPU batch-NFA vs the host-regex CPU baseline (BASELINE.json:
"Target: >=10x lines/sec vs Go regexp ... 32 patterns").

Prints ONE JSON line:
  {"metric": ..., "value": <device pipelined lines/sec>,
   "unit": "lines/sec", "vs_baseline": <value / cpu-regex lines/sec>,
   "detail": {...}}

Measurement notes (this environment): the TPU is attached through a
tunnel with ~74 ms round-trip per synchronous dispatch and ~35 MB/s
host->device bandwidth, so per-batch blocking times measure the tunnel,
not the engine. The headline value is therefore the SUSTAINED rate of
the device pipeline: N batches dispatched back-to-back (async), one
block at the end — the rate the async production sink sees once
transfers overlap compute. `detail.e2e_lps` is the fully synchronous
path (pack + ship + match + fetch per batch) on the same attach;
`detail.cpu_lps` is the host-regex baseline on the same lines.

Sizes are env-tunable for smoke runs: KLOGS_BENCH_LINES (default 300000
for the host-side CPU baseline; the device subprocess defaults it to the
device batch so the advertised operating point is actually measured —
set it only to shrink smoke runs), KLOGS_BENCH_CPU_LINES (30000),
KLOGS_BENCH_REPEATS (3); the device batch
(KLOGS_BENCH_DEVICE_BATCH, 1048576; on a CPU-only host 2048, where the
jnp path is a tiny smoke and the reported value is the host-regex
production path — see main()) and pipeline depth
(KLOGS_BENCH_N_FLIGHT, 64 on TPU / 2 on CPU) sit at the measured knee of the 2026-07-30
operating-point sweep (OPERATING_POINT.json, tools/bench_operating_point
.py): the fixed per-measurement sync cost (~151 ms; per-dispatch is only
~61 us) amortizes until the batch x depth curve flattens at ~8.6M
lines/s — 98% of the sweep's fitted engine-only ceiling (~8.74M).
Smaller operating points measure the sync, not the engine (BASELINE.md
caveats).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from klogs_tpu.cluster.fake import synthetic_line  # noqa: E402
from klogs_tpu.filters.cpu import RegexFilter  # noqa: E402
from klogs_tpu.utils.env import is_set as env_is_set  # noqa: E402
from klogs_tpu.utils.env import read as env_read  # noqa: E402

# 32 patterns, per the north-star config. Deliberately needle-finding:
# a log filter's purpose is selecting RARE lines, so most patterns match
# few or no lines (the CPU baseline must then try all K patterns per
# line — its real worst case — while the NFA cost is match-rate-free).
PATTERNS = [
    "panic:", "oom-killer", "segfault", "kernel:", "watchdog",
    "connection refused", "deadline exceeded", "unauthorized", "forbidden",
    "disk .*full", r"timeout|timed out", "TRACE", "FATAL", "backoff",
    r"retry \d+/\d+", r"GET /api/v\d+ 404", r"x-request-id: [0-9a-f]+",
    r"uid=\d{5,}", r"latency=49\dms", r"code=50[34]", r"seq=99999",
    r"ERROR.*path=/api/v2/admin", r"WARN.*latency=4[89]\dms",
    r"c[0-9]+ seq=123456", "failed path=/api/v9", r"5[12]\d [A-Z]{4,}",
    r"\d+ms code=418", "ECONNRESET", "EPIPE", "broken pipe",
    r"(?:FATAL|CRIT).*code=\d+", r"msg=\"request failed path=/api/v1/items\"",
]


def make_lines(n: int) -> list[bytes]:
    # Deterministic synthetic pod logs, ~128B each — the FakeCluster line
    # shape at 256-pod scale (SURVEY.md §6 config 3).
    out = []
    per_pod = max(1, n // 256)
    i = 0
    for p in range(256):
        pod = f"pod-{p:04d}"
        for s in range(per_pod):
            out.append(synthetic_line(pod, "c0", s, 1_753_800_000 + s))
            i += 1
            if i >= n:
                return out
    return out


# -- thousand-pattern K-axis (BENCH_K.json) ---------------------------
#
# Production alerting sets run thousands of patterns (ROADMAP item 2);
# `python bench.py --k-axis` measures K as a first-class axis: the
# factor-index engine (filters/indexed.py) vs the scan-all-K
# configuration of the SAME compiled groups — same tables, same
# engines, only the candidate narrowing differs — on the needle-finding
# corpus. Per-K rows report lines/s, lines/s*pattern (work units:
# pattern verdicts per second), and the candidate-narrowing ratio.

BENCH_K_DEFAULT = (32, 256, 1024, 4096)


def make_patterns(k: int) -> "list[str]":
    """K needle-finding patterns: the 32 north-star patterns plus
    minted alerting-rule families (distinct literals, realistic
    shapes — service/tenant/job ids nothing in the corpus matches).
    Deterministic; make_patterns(32) == PATTERNS."""
    out = list(PATTERNS)
    fam = [
        lambda i: f"svc-{i:04d} unreachable",
        lambda i: rf"errcode={i:05d}\b",
        lambda i: f"tenant-{i:04d}.*quota exceeded",
        lambda i: rf"CRIT{i:05d}",
        lambda i: rf"trace=[0-9a-f]+ span={i:06d}",
        lambda i: f"deploy/rel-{i:04d} failed",
        lambda i: rf"(?:FATAL|PANIC) job-{i:05d}",
        lambda i: rf"user=u{i:06d} denied",
    ]
    i = 0
    while len(out) < k:
        out.append(fam[i % len(fam)](i))
        i += 1
    return out[:k]


_SIMD_NAMES = {0: "scalar", 1: "ssse3", 2: "avx2", 3: "avx512"}


def _cpu_model() -> str:
    """Human CPU identification for BENCH_SWEEP rows: the native-sweep
    number depends on the SIMD level and the core, so rows are only
    comparable across machines when both are recorded."""
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.startswith("model name"):
                    return ln.split(":", 1)[1].strip()
    except OSError:
        pass
    import platform

    return platform.processor() or platform.machine()


def bench_sweep_rows(filt, payload: bytes, offsets, k: int,
                     repeats: int) -> "list[dict]":
    """Sweep-STAGE-only throughput for one K (BENCH_SWEEP.json): one
    row per implementation — ``numpy`` (the vectorized fallback and
    parity oracle), ``native`` (the SIMD kernel in _hostops.c, with
    the resolved stage-1 tier and CPU model recorded), ``device`` (the
    fused on-device sweep, with the jax backend recorded — on the CPU
    backend the dense sweep is gather-bound and LOSES to both host
    paths; that measurement is why auto mode only flips the device
    path on real accelerators) — over the same framed corpus, so the
    narrowing stage has its own trajectory separate from the
    end-to-end rows in BENCH_K.json.

    Every non-oracle row re-asserts mask parity against the numpy
    sweep on the corpus: a throughput row for a sweep that disagrees
    would be noise. Missing implementations (no C toolchain, no jax)
    degrade to fewer rows with a stderr note — the numpy trajectory
    is meaningful alone."""
    import numpy as np

    from klogs_tpu.filters.base import pack_framed_rows

    n = len(offsets) - 1
    base = {
        "k": k,
        "n_lines": n,
        "cpu_model": _cpu_model(),
        "n_factors": filt.index.n_factors,
        "n_groups": len(filt.groups),
        "simd": None,
        "backend": None,
        "pack_lps": None,
        # Stage-1 bucket mode and its survivor fraction (survivors /
        # scanned positions) — native rows only; the 8-vs-16 A/B pair
        # below quantifies the fat-Teddy cut on the same warmed index.
        "buckets": None,
        "survivor_ratio": None,
        # Sweep-stage rows time the index call directly — the slab
        # pipeline (KLOGS_SWEEP_PIPELINE) never runs here, so the
        # stage numbers stay schedule-independent.
        "pipeline_depth": 1,
    }

    def best_of(run):
        best, out = 0.0, None
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = run()
            best = max(best, n / (time.perf_counter() - t0))
        return best, out

    numpy_lps, gm_ref = best_of(
        lambda: filt.index.group_candidates(payload, offsets,
                                            impl="numpy"))
    rows = [dict(base, sweep_impl="numpy",
                 sweep_lps=round(numpy_lps, 1), vs_numpy=1.0,
                 parity=True)]
    msg = f"bench: K={k} sweep numpy={numpy_lps:,.0f} l/s"

    from klogs_tpu import native as _native
    from klogs_tpu.filters.compiler.index import native_simd_level

    level = native_simd_level()
    if (_native.hostops is not None
            and hasattr(_native.hostops, "sweep_candidates")
            and level is not None):
        from klogs_tpu.filters.compiler.index import native_sweep_buckets

        simd = _SIMD_NAMES.get(
            int(_native.hostops.sweep_simd_level(int(level))), "scalar")

        def native_row(pin=None):
            """One native-sweep row. ``pin`` pins KLOGS_SWEEP_BUCKETS
            (saved/restored) so the 8-vs-16 stage-1 A/B runs on the
            SAME warmed index — the blob cache keys by bucket count."""
            saved = env_read("KLOGS_SWEEP_BUCKETS")
            if pin is not None:
                os.environ["KLOGS_SWEEP_BUCKETS"] = str(pin)
            try:
                buckets = native_sweep_buckets(filt.index.n_factors)
                lps, gm = best_of(
                    lambda: filt.index.group_candidates(
                        payload, offsets, impl="native"))
                st = filt.index.last_sweep_stats or {}
                ratio = (st["survivors"] / st["positions"]
                         if st.get("positions") else None)
                return dict(
                    base, sweep_impl="native",
                    sweep_lps=round(lps, 1),
                    vs_numpy=round(lps / numpy_lps, 2)
                    if numpy_lps else None,
                    parity=bool(np.array_equal(gm_ref, gm)),
                    simd=simd, buckets=buckets,
                    survivor_ratio=round(ratio, 5)
                    if ratio is not None else None)
            finally:
                if pin is not None:
                    if saved is None:
                        os.environ.pop("KLOGS_SWEEP_BUCKETS", None)
                    else:
                        os.environ["KLOGS_SWEEP_BUCKETS"] = saved

        nat = native_row()
        rows.append(nat)
        msg += (f" native[{simd},{nat['buckets']}b]="
                f"{nat['sweep_lps']:,.0f} l/s parity={nat['parity']}")
        if nat["buckets"] == 16:
            # Fat-K corpora get the thin-kernel comparison row: same
            # index, same corpus, 8 buckets pinned — the survivor_ratio
            # pair is the measured fat-Teddy narrowing win.
            thin = native_row(pin=8)
            rows.append(thin)
            msg += (f" native[8b]={thin['sweep_lps']:,.0f} l/s "
                    f"survivors {thin['survivor_ratio']}"
                    f"->{nat['survivor_ratio']}")
    else:
        msg += " native=unavailable (no toolchain or KLOGS_NATIVE_SIMD=off)"

    try:
        import jax
        import jax.numpy as jnp

        from klogs_tpu.ops.sweep import (
            device_sweep_tables,
            sweep_group_candidates,
        )
    except ImportError:
        print(msg + " device=unavailable (no jax)", file=sys.stderr)
        return rows

    st = device_sweep_tables(filt.index.sweep_program())
    lens = np.diff(np.asarray(offsets)).astype(np.int32)
    width = 128
    while width < int(lens.max() if n else 1):
        width *= 2
    t0 = time.perf_counter()
    batch, _ = pack_framed_rows(payload, offsets, width)
    pack_lps = n / (time.perf_counter() - t0)
    batch_d = jnp.asarray(batch)
    lens_d = jnp.asarray(lens)
    gm_dev = np.asarray(sweep_group_candidates(st, batch_d, lens_d))
    dev_best, _ = best_of(
        lambda: jax.block_until_ready(
            sweep_group_candidates(st, batch_d, lens_d)))
    parity = bool(np.array_equal(gm_ref, gm_dev))
    rows.append(dict(base, sweep_impl="device",
                     sweep_lps=round(dev_best, 1),
                     vs_numpy=round(dev_best / numpy_lps, 3)
                     if numpy_lps else None,
                     parity=parity, backend=jax.default_backend(),
                     pack_lps=round(pack_lps, 1)))
    print(msg + f" device[{jax.default_backend()}]={dev_best:,.0f} l/s "
          f"parity={parity}", file=sys.stderr)
    return rows


def bench_k_axis(ks=None, n_lines: "int | None" = None,
                 repeats: "int | None" = None,
                 sweep_rows: "list | None" = None) -> dict:
    """One row per K (module comment above). Returns the BENCH_K
    payload; env knobs KLOGS_BENCH_K (comma-separated Ks),
    KLOGS_BENCH_K_LINES, KLOGS_BENCH_REPEATS shrink smoke runs.
    ``sweep_rows``, when a list, additionally collects the per-K
    sweep-stage-only rows (bench_sweep_row) for BENCH_SWEEP.json —
    measured here so the K=4096 index build is paid once."""
    import numpy as np

    from klogs_tpu.filters.base import frame_lines
    from klogs_tpu.filters.cpu import best_host_filter
    from klogs_tpu.filters.indexed import IndexedFilter

    if ks is None:
        env = env_read("KLOGS_BENCH_K", "")
        ks = tuple(int(x) for x in env.split(",") if x) or BENCH_K_DEFAULT
    n_lines = n_lines or int(env_read("KLOGS_BENCH_K_LINES", "100000"))
    repeats = repeats or int(env_read("KLOGS_BENCH_REPEATS", "3"))
    lines = [ln.rstrip(b"\n") for ln in make_lines(n_lines)]
    payload, offsets, _ = frame_lines(lines)
    offsets = np.asarray(offsets, dtype=np.int32)

    def rate(filt) -> "tuple[float, int, np.ndarray]":
        best, matched, v = 0.0, 0, np.zeros(0, dtype=bool)
        for _ in range(repeats):
            t0 = time.perf_counter()
            v = np.asarray(filt.fetch_framed(
                filt.dispatch_framed(payload, offsets)))
            best = max(best, len(lines) / (time.perf_counter() - t0))
            matched = int(v.sum())
        return best, matched, v

    rows = []
    for k in ks:
        pats = make_patterns(k)
        t0 = time.perf_counter()
        # sweep="host" pins the K rows to the HOST narrowing stage on
        # every machine: bench_sweep_row imports jax, which would flip
        # later Ks' auto mode onto the device sweep on an accelerator
        # host and mix two narrowing stages across one trajectory (the
        # device stage has its own rows in BENCH_SWEEP.json).
        filt = IndexedFilter(pats, sweep="host")
        build_s = time.perf_counter() - t0
        # Pin the adaptive bypass OFF for the measurement: the K=32
        # row's ratio (0.67) trips it mid-run, and a bypassed filter
        # times scan-all while the row claims to time the index. The
        # bypass is the production remedy for that row, not part of
        # the index-vs-scan-all comparison (it has its own tests).
        filt._bypass_min_lines = 1 << 62
        if sweep_rows is not None:
            sweep_rows.extend(
                bench_sweep_rows(filt, payload, offsets, k, repeats))
        # Per-stage attribution of the indexed measurement (sweep /
        # group-scan confirm / combined-re remainder), reset here so
        # the breakdown covers exactly the timed repeats. The adaptive
        # re-guard stays LIVE (unlike the bypass it keeps the index
        # narrowing — it IS the steady-state production path; its
        # probation slab is inside repeat 1 and best-of picks the
        # warmed repeats).
        for stage in filt.stage_s:
            filt.stage_s[stage] = 0.0
        idx_lps, idx_matched, idx_verd = rate(filt)
        stage_s = dict(filt.stage_s)
        ratio = filt.narrowing_ratio
        # Scan-all comparator: SAME groups/tables, narrowing off.
        filt.narrow = False
        all_lps, all_matched, all_verd = rate(filt)
        filt.narrow = True
        parity = bool(np.array_equal(idx_verd, all_verd))
        assert parity, (
            f"K={k}: indexed verdicts diverged "
            f"({idx_matched} vs {all_matched})")
        # The production auto path (best_host_filter): below
        # INDEX_MIN_K this is the unchanged single-DFA engine — the
        # K=32 row IS the no-regression check against the current
        # bench path. When auto provably resolves to the indexed
        # engine (no ambient overrides, K past the threshold), reuse
        # the measurement above instead of rebuilding an identical
        # IndexedFilter — at K=4096 that second build alone costs
        # ~60s.
        from klogs_tpu.filters.cpu import INDEX_MIN_K

        auto_is_indexed = (
            env_read("KLOGS_CPU_ENGINE", "auto") == "auto"
            and not env_is_set("KLOGS_INDEX_MIN_K")
            and k >= INDEX_MIN_K)
        if auto_is_indexed:
            auto_kind, auto_lps = "indexed", idx_lps
        else:
            auto, auto_kind = best_host_filter(pats)
            auto_lps = rate(auto)[0]
        rows.append({
            "k": k,
            "n_lines": len(lines),
            # Which narrowing implementation the host engine actually
            # ran (native vs numpy): K rows are only comparable across
            # machines when this matches. pipeline_depth is the slab
            # schedule the e2e row ran (1 = serial; KLOGS_SWEEP_PIPELINE
            # auto resolves per host core count).
            "sweep_impl": filt.index.last_impl,
            "pipeline_depth": filt._pipe_depth,
            # Per-stage seconds across the indexed measurement's
            # repeats, plus which confirm implementation ran — the
            # next PR reads where the remaining time goes.
            "sweep_s": round(stage_s["sweep"], 3),
            "group_scan_s": round(stage_s["group_scan"], 3),
            "merge_s": round(stage_s["merge"], 3),
            "group_scan_impl": filt.group_scan_impl,
            # Full indexed-vs-scan-all mask equality (not just counts).
            "parity": parity,
            # Guard factors the adaptive re-guard banned mid-run (0 =
            # the static index was already well-tuned for the corpus).
            "banned_factors": len(filt.banned_factors),
            "indexed_lps": round(idx_lps, 1),
            "scan_all_lps": round(all_lps, 1),
            "speedup_vs_scan_all": round(idx_lps / all_lps, 2),
            "lps_pattern": round(idx_lps * k, 1),
            "narrowing_ratio": round(ratio, 5),
            "auto_engine": auto_kind,
            "auto_lps": round(auto_lps, 1),
            "n_groups": len(filt.groups),
            "engine_kinds": filt.engine_kinds,
            "n_factors": filt.index.n_factors,
            "build_s": round(build_s, 2),
            "matched": idx_matched,
        })
        print(f"bench: K={k} indexed={idx_lps:,.0f} l/s "
              f"scan-all={all_lps:,.0f} l/s "
              f"({idx_lps / all_lps:.1f}x) narrowing={ratio:.4f} "
              f"auto={auto_kind}@{auto_lps:,.0f}", file=sys.stderr)
    return {
        "metric": "K-axis: lines/sec filtered vs pattern-set size "
                  "(factor-index engine vs scan-all-K, same groups)",
        "unit": "lines/sec",
        "corpus": "needle-finding synthetic pod logs, ~128B lines",
        "rows": rows,
    }


def cpu_lps(lines, repeats: int) -> float:
    filt = RegexFilter(PATTERNS)
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        filt.match_lines(lines)
        best = max(best, len(lines) / (time.perf_counter() - t0))
    return best


def cpu_strong_lps(lines, repeats: int):
    """(rate, engine_kind) of the STRONG host baseline — the fastest
    CPU engine this repo can build for the pattern set (native DFA
    scan; filters/cpu.best_host_filter). The round-4 verdict called
    the K-sequential-`re` multiple soft; the headline vs_baseline now
    cites this engine, with the K-sequential figure kept in detail."""
    from klogs_tpu.filters.cpu import best_host_filter

    filt, kind = best_host_filter(PATTERNS)
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        filt.match_lines(lines)
        best = max(best, len(lines) / (time.perf_counter() - t0))
    return best, kind


def measure_pipelined(run, n_rows: int, n_flight: int, repeats: int) -> float:
    """Best-of-`repeats` sustained rate of `run()` with `n_flight`
    dispatches in flight: block on the last output only, fetch ONE
    representative mask (fetching all would serialize n_flight tunnel
    round-trips and measure the attach, not the engine — module
    docstring). Shared by bench.py's headline and
    tools/bench_operating_point.py so their numbers stay comparable."""
    import numpy as np

    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = [run() for _ in range(n_flight)]
        outs[-1].block_until_ready()
        np.asarray(outs[-1])
        dt = time.perf_counter() - t0
        best = max(best, n_flight * n_rows / dt)
    return best


def device_lps(lines, repeats: int):
    """Returns {"pipelined", "e2e", "host_prep"} (lines/sec each).
    Pipelined: host-classified batches resident on device, N kernel
    dispatches in flight, one sync — the engine rate. host_prep: the
    fused host pack+classify pass (pipelines with device work in the
    async service, so sustained production rate ~ min(host_prep,
    pipelined) when transfers aren't the bottleneck). E2E: the
    synchronous NFAEngineFilter.match_lines path including
    pack/classify/ship/fetch — tunnel-RTT-bound in this environment."""
    import jax
    import numpy as np

    from klogs_tpu.filters.tpu import NFAEngineFilter, pack_classify, pack_lines
    from klogs_tpu.ops import nfa

    use_kernel = jax.default_backend() != "cpu"
    bodies = [ln.rstrip(b"\n") for ln in lines]
    host_prep = 0.0

    if use_kernel:
        from klogs_tpu.ops.pallas_nfa import match_cls_grouped_pallas

        dp, live, acc = nfa.compile_grouped(PATTERNS)
        table = np.asarray(dp.byte_class).astype(np.int8)
        t0 = time.perf_counter()
        cls = pack_classify(bodies, 128, table, dp.begin_class,
                            dp.end_class, dp.pad_class)
        host_prep = len(bodies) / (time.perf_counter() - t0)
        dcls = jax.device_put(cls)
        n_rows = cls.shape[0]
        from klogs_tpu.ops.tune import kernel_kwargs

        # Measured hardware default (mask_block=4) unless the env picks
        # a variant; the tune sweep below overwrites when enabled.
        kw = kernel_kwargs(on_hardware=True)
        if env_read("KLOGS_BENCH_TUNE") == "1":
            from klogs_tpu.ops.tune import tune_grouped

            best = tune_grouped(dp, live, acc, None, None, cls=dcls,
                                quiet=False)
            kw = {k: v for k, v in best.items() if k != "lines_per_s"}
        # KLOGS_TPU_PREFILTER=1 opts into the two-phase path (class-
        # domain candidate mask gates kernel tiles). Default OFF per the
        # 2026-07-29 device A/B (BENCH_DEVICE.json): with classification
        # moved to the host, the NFA kernel is no longer the bottleneck
        # and the mask cannot pay for itself.
        if env_read("KLOGS_TPU_PREFILTER", "0") == "1":
            from klogs_tpu.filters.compiler.prefilter import compile_prefilter
            from klogs_tpu.ops.prefilter import class_tables

            pf = compile_prefilter(PATTERNS)
            if pf.usable:
                ct = class_tables(pf, dp.byte_class, dp.n_classes)
                if ct is not None:
                    kw["prefilter_tables"] = ct
        run = lambda: match_cls_grouped_pallas(dp, live, acc, dcls, **kw)
        if "prefilter_tables" in kw:
            try:
                run().block_until_ready()
            except Exception as e:  # Mosaic/compile trouble: fall back
                print(f"bench: prefiltered kernel failed ({str(e)[:120]}); "
                      "falling back to plain NFA", file=sys.stderr)
                kw.pop("prefilter_tables")
    else:
        from klogs_tpu.filters.compiler.glushkov import compile_patterns

        batch, lengths = pack_lines(bodies, 128)
        db, dl = jax.device_put(batch), jax.device_put(lengths)
        n_rows = batch.shape[0]
        dpu = nfa.pack_program(compile_patterns(PATTERNS))
        run = lambda: nfa.match_batch(dpu, db, dl)

    np.asarray(run())  # warmup / compile
    # A CPU-only host runs the single-core jnp scan path: a deep pipeline
    # just multiplies wall time without amortizing anything (no async
    # device, no tunnel), so keep it shallow there.
    n_flight = int(env_read("KLOGS_BENCH_N_FLIGHT",
                                  "2" if not use_kernel else "64"))
    pipelined = measure_pipelined(run, n_rows, n_flight, repeats)

    filt = NFAEngineFilter(PATTERNS)
    filt.match_lines(lines[:4096])  # warm the jit caches
    t0 = time.perf_counter()
    filt.match_lines(lines)
    e2e = len(lines) / (time.perf_counter() - t0)
    return {"pipelined": pipelined, "e2e": e2e, "host_prep": host_prep}


def _device_subprocess(timeout_s: float):
    """Run the device measurement in a child process, retrying until the
    timeout budget is spent. A wedged TPU attach hangs inside backend
    init (C code) — in-process timeouts cannot interrupt it — and the
    wedge is transient: it clears with waiting, so one shot wastes the
    budget. The child prints ATTACHED as soon as ``jax.devices()``
    returns; only that attach phase runs on a short per-attempt timer
    (wedges manifest there). Once attached, the child keeps the whole
    remaining budget, so a slow-but-healthy measurement (big batch, tune
    sweep, slow remote compiles) is never killed mid-run. Returns
    (pipelined, e2e) or None once the budget is exhausted."""
    import subprocess

    code = (
        "import json, os, sys;"
        "import jax;"
        # An explicit CPU request must win even against an eagerly
        # registered TPU PJRT plugin (axon's sitecustomize monkeypatches
        # get_backend, so the env var alone still attaches — and hangs
        # when the tunnel is wedged); the config knob wins.
        "os.environ.get('JAX_PLATFORMS')=='cpu' and "
        "jax.config.update('jax_platforms','cpu');"
        "jax.devices();"
        "print('ATTACHED', flush=True);"
        "import bench;"
        "cpu=jax.default_backend()=='cpu';"
        # A CPU-only host has no production device path (the CLI's
        # --backend=cpu IS the host-regex engine there); the union-NFA
        # jnp path is quadratic in states (~1.4k lines/s single-core),
        # so run it tiny — enough to prove the path executes — and let
        # main() report the host-regex number as the honest value.
        "b=int(os.environ.get('KLOGS_BENCH_DEVICE_BATCH',"
        "'2048' if cpu else '1048576'));"
        "n=int(os.environ.get('KLOGS_BENCH_LINES','0')) or b;"
        "r=int(os.environ.get('KLOGS_BENCH_REPEATS','1' if cpu else '3'));"
        "lines=bench.make_lines(min(n,b));"
        "res=bench.device_lps(lines,r);"
        "res['backend']=jax.default_backend();"
        "print('RESULT:'+json.dumps(res))"
    )
    import selectors
    import tempfile

    attach_s = float(env_read("KLOGS_BENCH_DEVICE_ATTACH_S", "120"))
    retry_pause_s = float(env_read("KLOGS_BENCH_DEVICE_RETRY_PAUSE_S", "45"))
    deadline = time.monotonic() + timeout_s
    attempt = 0
    while attempt == 0 or deadline - time.monotonic() > 5:
        attempt += 1
        # stderr goes to a temp FILE, not a pipe: a chatty child (libtpu
        # warning storms, compile logs) would fill a 64KB pipe we don't
        # drain and deadlock before ever printing RESULT — and the file
        # keeps diagnostics for every failure mode, including kills.
        with tempfile.TemporaryFile(mode="w+") as errf:
            proc = subprocess.Popen(
                [sys.executable, "-u", "-c", code],
                stdout=subprocess.PIPE, stderr=errf,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            attach_deadline = min(time.monotonic() + attach_s, deadline)
            attached = False
            result = None
            failure = None
            # Raw-fd reads + manual line splitting: a buffered readline()
            # would block past the watchdog on a partial line, and its
            # lookahead buffer would desync select().
            fd = proc.stdout.fileno()
            buf = b""
            sel = selectors.DefaultSelector()
            sel.register(fd, selectors.EVENT_READ)
            try:
                while result is None:
                    now = time.monotonic()
                    cutoff = deadline if attached else attach_deadline
                    if now >= cutoff:
                        phase = "measurement" if attached else "attach"
                        failure = f"{phase} timed out (killed)"
                        proc.kill()
                        break
                    if not sel.select(timeout=min(5.0, cutoff - now)):
                        continue
                    chunk = os.read(fd, 65536)
                    if chunk == b"":  # EOF: child exited
                        proc.wait()
                        failure = f"exited rc={proc.returncode}"
                        break
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if line.startswith(b"ATTACHED"):
                            attached = True
                        elif line.startswith(b"RESULT:"):
                            result = json.loads(line[len(b"RESULT:"):])
                            break
            finally:
                sel.close()
                if proc.poll() is None:
                    proc.kill()
                proc.wait()
            if result is not None:
                return result
            errf.seek(0)
            tail = errf.read().strip().splitlines()[-3:]
            print(f"bench: device attempt {attempt} {failure}: "
                  f"{' | '.join(tail)}", file=sys.stderr)
        if deadline - time.monotonic() > retry_pause_s:
            time.sleep(retry_pause_s)
    return None


def main() -> None:
    if "--k-axis" in sys.argv[1:]:
        sweep_rows: list = []
        payload = bench_k_axis(sweep_rows=sweep_rows)
        out_path = env_read("KLOGS_BENCH_K_OUT") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_K.json")
        with open(out_path, "w") as f:
            json.dump(payload, f, indent=1)
            f.write("\n")
        sweep_payload = {
            "metric": "narrowing-stage-only lines/sec per K and "
                      "sweep_impl: numpy vs native SIMD vs device "
                      "literal sweep (masks parity-checked against "
                      "the numpy oracle on the corpus)",
            "unit": "lines/sec",
            "corpus": payload["corpus"],
            "rows": sweep_rows,
        }
        sweep_out = env_read("KLOGS_BENCH_SWEEP_OUT") or \
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_SWEEP.json")
        with open(sweep_out, "w") as f:
            json.dump(sweep_payload, f, indent=1)
            f.write("\n")
        print(json.dumps(payload))
        return
    n_lines = int(env_read("KLOGS_BENCH_LINES", "300000"))
    n_cpu = int(env_read("KLOGS_BENCH_CPU_LINES", "30000"))
    repeats = int(env_read("KLOGS_BENCH_REPEATS", "3"))
    timeout_s = float(env_read("KLOGS_BENCH_DEVICE_TIMEOUT_S", "900"))

    lines = make_lines(n_lines)
    cpu = cpu_lps(lines[:n_cpu], repeats)
    strong, strong_kind = cpu_strong_lps(lines, repeats)
    dev = _device_subprocess(timeout_s)

    base_detail = {
        "cpu_regex_lps": round(cpu, 1),
        "cpu_strong_lps": round(strong, 1),
        "cpu_strong_engine": strong_kind,
        "baseline": f"strong-cpu ({strong_kind})",
        "n_patterns": len(PATTERNS),
        "line_width_bytes": 128,
    }
    if dev is not None and dev.get("backend") == "cpu":
        # No TPU on this host: the production --backend=cpu path IS the
        # strong host engine; the tiny jnp run only proves the device
        # code path executes. Report the honest production number.
        print(json.dumps({
            "metric": "log-lines/sec filtered, 32 patterns x 256-pod batch (batch-NFA)",
            "value": round(strong, 1),
            "unit": "lines/sec",
            "vs_baseline": 1.0,
            "detail": dict(base_detail, no_tpu_on_host=True,
                           jnp_smoke_lps=round(dev["pipelined"], 1)),
        }))
    elif dev is not None:
        pipelined, e2e = dev["pipelined"], dev["e2e"]
        print(json.dumps({
            "metric": "log-lines/sec filtered, 32 patterns x 256-pod batch (batch-NFA)",
            "value": round(pipelined, 1),
            "unit": "lines/sec",
            # Round-4 verdict: cite the STRONG baseline, not the soft
            # K-sequential one (kept as vs_cpu_regex in detail).
            "vs_baseline": round(pipelined / strong, 3) if strong else None,
            "detail": dict(
                base_detail,
                device_pipelined_lps=round(pipelined, 1),
                host_pack_classify_lps=round(dev.get("host_prep", 0.0), 1),
                e2e_sync_lps=round(e2e, 1),
                vs_cpu_regex=round(pipelined / cpu, 3) if cpu else None,
            ),
        }))
    else:
        # Device attach unavailable/hung: report the CPU baseline so the
        # driver still gets a terminating, honest data point.
        print(json.dumps({
            "metric": "log-lines/sec filtered, 32 patterns x 256-pod batch (batch-NFA)",
            "value": round(strong, 1),
            "unit": "lines/sec",
            "vs_baseline": None,
            "detail": dict(base_detail, device_unavailable=True),
        }))


if __name__ == "__main__":
    main()
