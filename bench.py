"""North-star benchmark: log lines/sec filtered, K patterns x N-pod-scale
batches, TPU batch-NFA vs the host-regex CPU baseline (BASELINE.json:
"Target: >=10x lines/sec vs Go regexp ... 32 patterns").

Prints ONE JSON line:
  {"metric": ..., "value": <tpu lines/sec>, "unit": "lines/sec",
   "vs_baseline": <tpu / cpu-regex>}

Run on whatever jax platform is ambient (the driver provides the real
TPU chip). Sizes are env-tunable for smoke runs:
  KLOGS_BENCH_LINES (default 200000), KLOGS_BENCH_REPEATS (default 3),
  KLOGS_BENCH_CPU_LINES (default 20000).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from klogs_tpu.cluster.fake import synthetic_line  # noqa: E402
from klogs_tpu.filters.cpu import RegexFilter  # noqa: E402
from klogs_tpu.filters.tpu import NFAEngineFilter  # noqa: E402

PATTERNS = [
    "ERROR", r"WARN.*\d", "^2026-", r"timeout|timed out", r"code=5\d{2}",
    r"latency=\d{3,}ms", "panic:", "oom-killer", "connection refused",
    r"retry \d+/\d+", r"GET /api/v\d+ 404", r"disk .*full",
    r"\d+ms code=400", "failed path=/api/v1", "seq=99", r"c[0-9]+ seq=1\d\d",
    "TRACE", "FATAL", r"^\d{4}-\d{2}-\d{2}T", "kernel:", "segfault",
    r"uid=\d+", "unauthorized", "forbidden", r"5\d\d [A-Z]+",
    "deadline exceeded", r"x-request-id: [0-9a-f]+", "EOF",
    r"(?:ERROR|FATAL).*code=\d+", "watchdog", "backoff", r"\[\d+\]",
]  # 32 patterns, per the north-star config


def make_lines(n: int) -> list[bytes]:
    # Deterministic synthetic pod logs, ~128B each — the FakeCluster line
    # shape at 256-pod scale (SURVEY.md §6 config 3).
    out = []
    per_pod = max(1, n // 256)
    i = 0
    for p in range(256):
        pod = f"pod-{p:04d}"
        for s in range(per_pod):
            out.append(synthetic_line(pod, "c0", s, 1_753_800_000 + s))
            i += 1
            if i >= n:
                return out
    return out


def timed_lps(filt, lines, repeats: int, chunk: int = 8192) -> float:
    # One warmup pass over a prefix to absorb jit compilation.
    filt.match_lines(lines[: min(len(lines), chunk)])
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        n = 0
        for i in range(0, len(lines), chunk):
            n += len(filt.match_lines(lines[i : i + chunk]))
        dt = time.perf_counter() - t0
        best = max(best, n / dt)
    return best


def main() -> None:
    n_lines = int(os.environ.get("KLOGS_BENCH_LINES", "200000"))
    n_cpu = int(os.environ.get("KLOGS_BENCH_CPU_LINES", "20000"))
    repeats = int(os.environ.get("KLOGS_BENCH_REPEATS", "3"))

    lines = make_lines(n_lines)
    cpu_lps = timed_lps(RegexFilter(PATTERNS), lines[:n_cpu], repeats)
    tpu_lps = timed_lps(NFAEngineFilter(PATTERNS), lines, repeats)

    print(json.dumps({
        "metric": "log-lines/sec filtered, 32 patterns x 256-pod batch (batch-NFA)",
        "value": round(tpu_lps, 1),
        "unit": "lines/sec",
        "vs_baseline": round(tpu_lps / cpu_lps, 3) if cpu_lps else None,
    }))


if __name__ == "__main__":
    main()
